"""Dashboard-lite — the REST surface of the reference dashboard.

Reference: dashboard/head.py + modules/snapshot (REST API over GCS
state) + the metrics exporter. Serves JSON state endpoints and the
Prometheus text endpoint from one stdlib HTTP server:

    /api/cluster_status   nodes + resources
    /api/nodes            node table
    /api/actors           actor table
    /api/placement_groups PG table
    /api/objects          ownership/object table
    /api/events           structured event log
    /metrics              Prometheus exposition
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        outer_routes = self._routes()

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                path = self.path.split("?")[0].rstrip("/") or "/"
                fn = outer_routes.get(path)
                if fn is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    body, content_type = fn()
                    self.send_response(200)
                    self.send_header("Content-Type", content_type)
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as e:  # noqa: BLE001
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(json.dumps(
                        {"error": str(e)}).encode())

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def _routes(self):
        def as_json(fn):
            def inner() -> Tuple[bytes, str]:
                return (json.dumps(fn(), default=str).encode(),
                        "application/json")

            return inner

        def state():
            from ray_tpu.gcs import state as gcs_state

            return gcs_state

        def metrics() -> Tuple[bytes, str]:
            from ray_tpu.observability.metrics import prometheus_text

            return prometheus_text().encode(), "text/plain; version=0.0.4"

        def events():
            from ray_tpu.observability.events import global_event_log

            return global_event_log.list()

        return {
            "/api/cluster_status": as_json(lambda: {
                "nodes": state().node_table(),
                "cluster_resources": state().cluster_resources(),
                "available_resources": state().available_resources(),
            }),
            "/api/nodes": as_json(lambda: state().node_table()),
            "/api/actors": as_json(lambda: state().actor_table()),
            "/api/placement_groups": as_json(
                lambda: state().placement_group_table()),
            "/api/objects": as_json(lambda: state().object_table()),
            "/api/events": as_json(events),
            "/metrics": metrics,
        }


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> Dashboard:
    return Dashboard(host, port)
