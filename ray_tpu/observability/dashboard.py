"""Dashboard-lite — the REST surface of the reference dashboard.

Reference: dashboard/head.py + modules/snapshot (REST API over GCS
state) + the metrics exporter. Serves JSON state endpoints and the
Prometheus text endpoint from one stdlib HTTP server:

    /api/cluster_status   nodes + resources
    /api/nodes            node table
    /api/actors           actor table
    /api/placement_groups PG table
    /api/objects          ownership/object table
    /api/events           structured event log
    /metrics              Prometheus exposition
"""

from __future__ import annotations

import json
from typing import Optional, Tuple


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        from ray_tpu.observability.http_util import start_json_server

        routes = {path: (lambda fn: lambda query: fn())(fn)
                  for path, fn in self._routes().items()}
        self._server = start_json_server(routes, host, port)
        self.port = self._server.server_address[1]

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def _routes(self):
        def as_json(fn):
            def inner() -> Tuple[bytes, str]:
                return (json.dumps(fn(), default=str).encode(),
                        "application/json")

            return inner

        def state():
            from ray_tpu.gcs import state as gcs_state

            return gcs_state

        def metrics() -> Tuple[bytes, str]:
            from ray_tpu.observability.metrics import prometheus_text

            return prometheus_text().encode(), "text/plain; version=0.0.4"

        def events():
            from ray_tpu.observability.events import global_event_log

            return global_event_log.list()

        return {
            "/api/cluster_status": as_json(lambda: {
                "nodes": state().node_table(),
                "cluster_resources": state().cluster_resources(),
                "available_resources": state().available_resources(),
            }),
            "/api/nodes": as_json(lambda: state().node_table()),
            "/api/actors": as_json(lambda: state().actor_table()),
            "/api/placement_groups": as_json(
                lambda: state().placement_group_table()),
            "/api/objects": as_json(lambda: state().object_table()),
            "/api/events": as_json(events),
            "/metrics": metrics,
        }


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> Dashboard:
    return Dashboard(host, port)
