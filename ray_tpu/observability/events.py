"""Structured cluster events (reference: src/ray/util/event.{h,cc} +
dashboard/modules/event/): severity-labeled JSON records appended to a
per-process buffer and optionally a JSONL file, consumed by the
dashboard-lite state dump.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from enum import Enum
from typing import Any, Deque, Dict, List, Optional


class Severity(str, Enum):
    DEBUG = "DEBUG"
    INFO = "INFO"
    WARNING = "WARNING"
    ERROR = "ERROR"
    FATAL = "FATAL"


class EventLog:
    def __init__(self, max_events: int = 10_000,
                 file_path: Optional[str] = None):
        self._events: Deque[Dict[str, Any]] = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._file_path = file_path
        self._counter = 0

    def emit(self, label: str, message: str,
             severity: Severity = Severity.INFO,
             **fields: Any) -> Dict[str, Any]:
        with self._lock:
            self._counter += 1
            event = {
                "event_id": self._counter,
                "timestamp": time.time(),
                "severity": str(severity.value
                                if isinstance(severity, Severity)
                                else severity),
                "label": label,
                "message": message,
                "pid": os.getpid(),
                **fields,
            }
            self._events.append(event)
            if self._file_path:
                with open(self._file_path, "a") as f:
                    f.write(json.dumps(event) + "\n")
        if event["severity"] == "FATAL":
            # A typed fatal error dumps the flight recorder while the
            # process can still write (outside self._lock: the recorder
            # has its own locking and may touch metrics/config).
            try:
                from ray_tpu.observability import flight_recorder
                flight_recorder.record_fatal(event)
            except Exception:
                pass
        return event

    def list(self, label: Optional[str] = None,
             min_severity: Optional[Severity] = None
             ) -> List[Dict[str, Any]]:
        order = ["DEBUG", "INFO", "WARNING", "ERROR", "FATAL"]
        with self._lock:
            events = list(self._events)
        if label is not None:
            events = [e for e in events if e["label"] == label]
        if min_severity is not None:
            threshold = order.index(min_severity.value)
            events = [e for e in events
                      if order.index(e["severity"]) >= threshold]
        return events

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


global_event_log = EventLog()


def emit(label: str, message: str, severity: Severity = Severity.INFO,
         **fields: Any) -> Dict[str, Any]:
    return global_event_log.emit(label, message, severity, **fields)
