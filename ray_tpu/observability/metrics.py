"""Metric registry: Counter/Gauge/Histogram + Prometheus text exporter.

Reference: src/ray/stats/metric.h:101 (C++ registry over OpenCensus,
definitions in metric_defs.cc) exported through the per-node
MetricsAgent (python/ray/_private/metrics_agent.py:65) to Prometheus
(:79). Here the registry is process-global and the exporter renders the
Prometheus text format directly; serve it with `start_metrics_server`.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}

DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60)


class Metric:
    TYPE = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._nil_key = tuple("" for _ in self.tag_keys)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], float] = {}
        with _registry_lock:
            existing = _registry.get(name)
            if existing is not None:
                # re-registration returns the same series storage
                self._series = existing._series
                self._lock = existing._lock
            _registry[name] = self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        if not tags:  # hot path: untagged series
            return self._nil_key
        return tuple(str(tags.get(k, "")) for k in self.tag_keys)

    def series(self) -> Dict[Tuple[str, ...], float]:
        with self._lock:
            return dict(self._series)


class Counter(Metric):
    TYPE = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        key = self._key(tags)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value


class Gauge(Metric):
    TYPE = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._series[self._key(tags)] = float(value)

    def record(self, value: float,
               tags: Optional[Dict[str, str]] = None) -> None:
        self.set(value, tags)


class Histogram(Metric):
    TYPE = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = DEFAULT_BUCKETS,
                 tag_keys: Sequence[str] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(sorted(boundaries))
        self._buckets: Dict[Tuple[str, ...], List[int]] = {}
        self._sum: Dict[Tuple[str, ...], float] = {}
        self._count: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = self._key(tags)
        idx = bisect.bisect_left(self.boundaries, value)
        with self._lock:
            if key not in self._buckets:
                self._buckets[key] = [0] * (len(self.boundaries) + 1)
            self._buckets[key][idx] += 1
            self._sum[key] = self._sum.get(key, 0.0) + value
            self._count[key] = self._count.get(key, 0) + 1

    record = observe

    def sum_value(self, tags: Optional[Dict[str, str]] = None) -> float:
        """Sum of all observed values for one tag series."""
        with self._lock:
            return self._sum.get(self._key(tags), 0.0)

    def count_value(self, tags: Optional[Dict[str, str]] = None) -> int:
        """Number of observations for one tag series."""
        with self._lock:
            return self._count.get(self._key(tags), 0)

    def percentile(self, q: float,
                   tags: Optional[Dict[str, str]] = None) -> Optional[float]:
        """Bucket-bound quantile estimate.

        Returns the *upper bound* of the first bucket whose cumulative
        count reaches ``q`` percent of observations — not an
        interpolated sample value. Consequences callers must expect:

        - A single-sample series returns that sample's bucket upper
          bound for every q (even q=50), which can exceed the sample.
        - Values above the last boundary land in the overflow bucket,
          so the estimate is ``float("inf")`` — there is no finite
          upper bound to report.
        - An empty series returns ``None``.

        This is the standard Prometheus-histogram trade-off: accuracy
        is limited to bucket resolution (``cli.py status`` p99 readouts
        are bucket bounds, not exact order statistics).
        """
        key = self._key(tags)
        with self._lock:
            buckets = self._buckets.get(key)
            count = self._count.get(key, 0)
        if not buckets or not count:
            return None
        target = q / 100.0 * count
        seen = 0
        for i, c in enumerate(buckets):
            seen += c
            if seen >= target:
                return (self.boundaries[i] if i < len(self.boundaries)
                        else float("inf"))
        return float("inf")


def get_metric(name: str) -> Optional[Metric]:
    with _registry_lock:
        return _registry.get(name)


def clear_registry() -> None:
    with _registry_lock:
        _registry.clear()


def _escape_tag_value(value: str) -> str:
    """Escape a tag value per the Prometheus text exposition format:
    backslash, double-quote, and line-feed must be escaped or a value
    containing them corrupts the whole scrape."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_le(bound: float) -> str:
    """Render a histogram ``le`` bound per the exposition spec: a float
    literal ("0.005", "1.0") or "+Inf" — never Python repr of an int."""
    if bound == float("inf"):
        return "+Inf"
    return repr(float(bound))


def _fmt_tags(keys: Sequence[str], values: Tuple[str, ...]) -> str:
    if not keys:
        return ""
    pairs = ",".join(f'{k}="{_escape_tag_value(v)}"'
                     for k, v in zip(keys, values))
    return "{" + pairs + "}"


def prometheus_text() -> str:
    """Render every registered metric in Prometheus exposition format."""
    with _registry_lock:
        metrics = list(_registry.values())
    lines: List[str] = []
    for m in metrics:
        lines.append(f"# HELP {m.name} {m.description}")
        lines.append(f"# TYPE {m.name} {m.TYPE}")
        if isinstance(m, Histogram):
            with m._lock:
                for key, buckets in m._buckets.items():
                    cum = 0
                    for b, c in zip(m.boundaries, buckets):
                        cum += c
                        tags = dict(zip(m.tag_keys, key))
                        tags["le"] = _fmt_le(b)
                        tag_str = ",".join(
                            f'{k}="{_escape_tag_value(v)}"'
                            if k != "le" else f'{k}="{v}"'
                            for k, v in tags.items())
                        lines.append(
                            f"{m.name}_bucket{{{tag_str}}} {cum}")
                    tags = dict(zip(m.tag_keys, key))
                    tags["le"] = "+Inf"
                    tag_str = ",".join(
                        f'{k}="{_escape_tag_value(v)}"'
                        if k != "le" else f'{k}="{v}"'
                        for k, v in tags.items())
                    lines.append(
                        f"{m.name}_bucket{{{tag_str}}} "
                        f"{m._count.get(key, 0)}")
                    base = _fmt_tags(m.tag_keys, key)
                    lines.append(
                        f"{m.name}_sum{base} {m._sum.get(key, 0.0)}")
                    lines.append(
                        f"{m.name}_count{base} {m._count.get(key, 0)}")
        else:
            for key, value in m.series().items():
                lines.append(
                    f"{m.name}{_fmt_tags(m.tag_keys, key)} {value}")
    return "\n".join(lines) + "\n"


def start_metrics_server(host: str = "127.0.0.1", port: int = 0):
    """Serve /metrics like the reference's per-node agent exporter."""
    import threading as _threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path.rstrip("/") in ("", "/metrics"):
                body = prometheus_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_response(404)
                self.end_headers()

    server = ThreadingHTTPServer((host, port), Handler)
    thread = _threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, server.server_address[1]


# ----------------------------------------------------- core named metrics
# (reference: src/ray/stats/metric_defs.cc — the system-level series)
tasks_submitted = Counter("ray_tpu_tasks_submitted",
                          "Tasks submitted to the scheduler")
tasks_finished = Counter("ray_tpu_tasks_finished", "Tasks finished")
scheduler_ticks = Counter("ray_tpu_scheduler_ticks",
                          "Batched scheduling ticks")
scheduling_latency = Histogram(
    "ray_tpu_scheduling_latency_s",
    "Submit-to-dispatch latency",
    boundaries=(1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0))
object_store_bytes = Gauge("ray_tpu_object_store_bytes",
                           "Bytes resident in the object store")
actors_alive = Gauge("ray_tpu_actors_alive", "Alive actors")

# ---- overload plane (cluster/overload.py + rpc.py admission control) ----
rpc_requests_shed = Counter(
    "ray_tpu_rpc_requests_shed",
    "RPC requests shed by server admission control "
    "(reason: queue_full | queue_deadline)",
    tag_keys=("reason",))
rpc_dispatch_queue_depth = Gauge(
    "ray_tpu_rpc_dispatch_queue_depth",
    "Requests waiting in the bounded RPC dispatch queue")
rpc_replies_dropped = Counter(
    "ray_tpu_rpc_replies_dropped",
    "Replies dropped because the client disconnected first")
rpc_retries_spent = Counter(
    "ray_tpu_rpc_retries_spent",
    "Client retries admitted by the per-destination retry budget")
rpc_retry_budget_exhausted = Counter(
    "ray_tpu_rpc_retry_budget_exhausted",
    "Client retries refused because the retry budget was empty")
rpc_breaker_transitions = Counter(
    "ray_tpu_rpc_breaker_transitions",
    "Circuit breaker state transitions", tag_keys=("to",))
tasks_shed = Counter(
    "ray_tpu_tasks_shed",
    "Task submissions pushed back by the bounded raylet queue")

# ---- fast-lane fault hardening (cluster/overload.py lane breakers) ------
fastlane_breaker_transitions = Counter(
    "ray_tpu_fastlane_breaker_transitions",
    "Per-lane degraded-mode breaker transitions: a lane flipping to "
    "its safe path (to=open) or probing back (to=closed)",
    tag_keys=("lane", "to"))
batch_rows_deduped = Counter(
    "ray_tpu_batch_rows_deduped",
    "Batch-frame rows answered from the per-row dedupe cache instead "
    "of re-applied (a retried frame after a lost ack or GCS restart)",
    tag_keys=("method",))
chunk_tree_failovers = Counter(
    "ray_tpu_chunk_tree_failovers",
    "Broadcast subtrees re-rooted around a dead or stalled relay node "
    "(parent re-offered the subtree from its sealed replica)")
tick_epoch_fences = Counter(
    "ray_tpu_tick_epoch_fences",
    "In-flight pipelined device solve batches discarded because the "
    "cluster topology epoch moved between launch and commit")
warm_specialize_crash_fallbacks = Counter(
    "ray_tpu_warm_specialize_crash_fallbacks",
    "Warm-lease actor creations whose leased worker died mid-"
    "specialization and were transparently retried as a cold fork")

# ---- serve resilience plane (serve/{controller,handle,replica}.py) ------
serve_replicas_unhealthy = Counter(
    "ray_tpu_serve_replicas_unhealthy",
    "Replicas that failed the controller's health probe "
    "health_check_failure_threshold consecutive times and were "
    "drained from routing and replaced")
serve_drains_completed = Counter(
    "ray_tpu_serve_drains_completed",
    "Graceful replica drains that reached zero in-flight requests "
    "before the graceful_shutdown_timeout_s kill")
serve_router_excluded = Counter(
    "ray_tpu_serve_router_excluded",
    "Replica candidates the serve router excluded from an assignment "
    "(reason: breaker_open | shed_penalty | saturated)",
    tag_keys=("reason",))
serve_requests_backpressured = Counter(
    "ray_tpu_serve_requests_backpressured",
    "Requests refused with BackpressureError because every replica "
    "was shedding, breaker-open, or saturated")

# ---- worker pool & actor lifecycle (cluster/process_pool.py + GCS) ------
worker_pool_warm_hits = Counter(
    "ray_tpu_worker_pool_warm_hits",
    "Actor creations served by leasing a pre-forked warm worker")
worker_pool_warm_misses = Counter(
    "ray_tpu_worker_pool_warm_misses",
    "Actor creations that cold-forked a fresh worker process "
    "(pool empty, stale lease, or warm pool disabled)")
worker_pool_size = Gauge(
    "ray_tpu_worker_pool_size",
    "Idle warm workers currently pre-forked in this node's pool")
actor_creates_batched = Counter(
    "ray_tpu_actor_creates_batched",
    "Actor creations that arrived coalesced in actor_create_batch "
    "frames (GCS-side)")
actor_kills_batched = Counter(
    "ray_tpu_actor_kills_batched",
    "Actor kills that arrived coalesced in actor_kill_batch frames "
    "(GCS-side)")
actor_create_latency_ms = Histogram(
    "ray_tpu_actor_create_latency_ms",
    "Raylet-side actor creation latency: lease/fork + class unpickle "
    "+ __init__, in milliseconds",
    boundaries=(1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000,
                15000, 60000))

# ---- integrity plane (cluster/integrity.py checksum seams) --------------
objects_corruption_detected = Counter(
    "ray_tpu_objects_corruption_detected",
    "Object payloads that failed checksum verification at a "
    "data-movement seam (push_end | push_chunk | pull_stream | "
    "shm_read | spill_restore | adopt_shm | orphan_reclaim | get)",
    tag_keys=("seam",))
corrupt_replicas_discarded = Counter(
    "ray_tpu_corrupt_replicas_discarded",
    "Corrupt object replicas discarded by the detecting holder "
    "(recovery re-pulls from another holder or reconstructs)")
integrity_bytes_verified = Counter(
    "ray_tpu_integrity_bytes_verified",
    "Payload bytes that passed checksum verification at a seam")

# ---- node drain / preemption plane (cluster/gcs_server.py drains) -------
nodes_draining = Gauge(
    "ray_tpu_nodes_draining",
    "Nodes currently in the DRAINING lifecycle state (graceful drain "
    "in progress: placements steered away, actors migrating, "
    "sole-copy objects re-replicating off-node)")
drains_completed = Counter(
    "ray_tpu_drains_completed",
    "Graceful node drains finished (outcome: graceful — migration and "
    "re-replication completed inside drain_deadline_s — or deadline — "
    "the drain fell back to the hard-kill recovery path)",
    tag_keys=("outcome",))
preemption_notices = Counter(
    "ray_tpu_preemption_notices",
    "Preemption notices received (raylet-side delivery and GCS-side "
    "heartbeat reports each count once, tagged by role)",
    tag_keys=("role",))
objects_rereplicated = Counter(
    "ray_tpu_objects_rereplicated",
    "Sole-copy objects successfully re-replicated off a draining node "
    "before its deregistration")

# ---- performance observability plane (util/tracing.py + rpc.py) ---------
# dst_kind is the serving process's role (gcs | raylet | worker |
# driver, cluster/fault_plane.py process_role) so the same method name
# is attributable per tier.
rpc_server_latency_ms = Histogram(
    "ray_tpu_rpc_server_latency_ms",
    "Server-side RPC handler time (dispatch to reply-ready), ms",
    boundaries=(0.05, 0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000, 5000),
    tag_keys=("method", "dst_kind"))
rpc_server_queue_ms = Histogram(
    "ray_tpu_rpc_server_queue_ms",
    "Time an RPC waited in the bounded dispatch queue before its "
    "handler ran, ms (inline fast-path methods observe 0)",
    boundaries=(0.05, 0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000, 5000),
    tag_keys=("method", "dst_kind"))
rpc_request_bytes = Histogram(
    "ray_tpu_rpc_request_bytes",
    "Serialized request frame size per method, bytes",
    boundaries=(64, 256, 1024, 4096, 16384, 65536, 262144,
                1 << 20, 4 << 20, 32 << 20),
    tag_keys=("method", "dst_kind"))
scheduler_phase_ms = Histogram(
    "ray_tpu_scheduler_phase_ms",
    "Per-phase wall time inside one batched scheduling tick "
    "(phase: collect | refresh | solve | commit | spillback | "
    "dispatch), ms",
    boundaries=(0.01, 0.05, 0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000),
    tag_keys=("phase",))
flight_recorder_dumps = Counter(
    "ray_tpu_flight_recorder_dumps",
    "Flight-recorder JSONL dumps written (reason: SIGUSR2 | "
    "uncaught | fatal_event | manual)",
    tag_keys=("reason",))
