"""Dashboard web UI — a dependency-free single page over the REST API.

Reference: dashboard/client/ (a React SPA consuming the dashboard REST
endpoints). This build serves the same information — cluster summary,
per-node resources/object-store/worker stats, the actor table, jobs and
live worker logs — as one self-contained HTML page with vanilla-JS
polling (no build step, no npm tree), which is the appropriate weight
for a head process whose API is already JSON. The REST surface stays
the contract; the page is a thin consumer like the reference SPA."""

INDEX_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  :root { color-scheme: light dark; }
  body { font-family: ui-monospace, SFMono-Regular, Menlo, monospace;
         margin: 1.5rem; line-height: 1.45; }
  h1 { font-size: 1.2rem; } h2 { font-size: 1rem; margin-top: 1.6rem; }
  table { border-collapse: collapse; width: 100%; font-size: 0.85rem; }
  th, td { border: 1px solid #8884; padding: 0.25rem 0.5rem;
           text-align: left; vertical-align: top; }
  th { background: #8882; }
  .ok { color: #2e7d32; } .bad { color: #c62828; }
  #logs { white-space: pre-wrap; font-size: 0.8rem; max-height: 20rem;
          overflow-y: auto; border: 1px solid #8884; padding: 0.5rem; }
  .muted { opacity: 0.65; font-size: 0.8rem; }
</style>
</head>
<body>
<h1>ray_tpu dashboard</h1>
<div class="muted">auto-refreshes every 2s; data from /api/cluster,
/api/nodes, /api/actors, /api/jobs, /api/logs</div>
<h2>Cluster</h2><div id="cluster">loading…</div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Recent logs</h2><div id="logs"></div>
<script>
const esc = (s) => s.replace(/[&<>"']/g, (c) => ({"&": "&amp;",
  "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;"}[c]));
// every value is escaped before innerHTML interpolation: actor names,
// job entrypoints and resource names are user-controlled strings and
// must never execute in the operator's browser
const fmt = (x) => x === null || x === undefined ? "" :
  esc(typeof x === "object" ? JSON.stringify(x) : String(x));
function table(el, rows, cols) {
  if (!rows.length) { el.innerHTML = "<tr><td class=muted>none</td></tr>"; return; }
  let html = "<tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("") + "</tr>";
  for (const r of rows)
    html += "<tr>" + cols.map(c => `<td>${fmt(r[c])}</td>`).join("") + "</tr>";
  el.innerHTML = html;
}
async function j(path) { const r = await fetch(path); return r.json(); }
async function refresh() {
  try {
    const [cluster, nodes, actors, jobs, logs] = await Promise.all([
      j("/api/cluster"), j("/api/nodes"), j("/api/actors"),
      j("/api/jobs"), j("/api/logs?n=200")]);
    const ns = Object.values(cluster.nodes || {});
    const alive = ns.filter(n => n.alive).length;
    document.getElementById("cluster").innerHTML =
      `<span class="${alive === ns.length ? "ok" : "bad"}">` +
      `${alive}/${ns.length} nodes alive</span>`;
    table(document.getElementById("nodes"),
      nodes.map(n => ({node: (n.node_id || "").slice(0, 8),
        resources: n.resources, available: n.available,
        queued: n.queued, running: n.running, store: n.store,
        workers: n.pool, agent: n.agent})),
      ["node", "resources", "available", "queued", "running",
       "store", "workers", "agent"]);
    table(document.getElementById("actors"),
      (actors || []).map(a => ({actor: (a.actor_id || "").slice(0, 8),
        name: a.name, state: a.state,
        node: (a.node_id || "").slice(0, 8),
        restarts: `${a.restarts_used}/${a.max_restarts}`})),
      ["actor", "name", "state", "node", "restarts"]);
    table(document.getElementById("jobs"),
      (jobs || []).map(jb => ({job: (jb.job_id || "").slice(0, 12),
        status: jb.status, entrypoint: jb.entrypoint})),
      ["job", "status", "entrypoint"]);
    document.getElementById("logs").textContent =
      (logs || []).map(l => `[${(l.node_id || "").slice(0, 8)}:` +
                            `${l.pid}] ${l.line}`).join("\\n");
  } catch (e) {
    document.getElementById("cluster").innerHTML =
      `<span class=bad>head unreachable: ${e}</span>`;
  }
}
refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
