"""ray_tpu.observability — metrics, events, profiling.

Reference surface: src/ray/stats/ (metric registry), src/ray/util/event
(structured events), core_worker/profiling + ``ray timeline``.
"""

from ray_tpu.observability.events import (  # noqa: F401
    EventLog,
    Severity,
    emit,
    global_event_log,
)
from ray_tpu.observability.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    get_metric,
    prometheus_text,
    start_metrics_server,
)
from ray_tpu.observability.dashboard_head import DashboardHead  # noqa: F401
from ray_tpu.observability.flight_recorder import (  # noqa: F401
    FlightRecorder,
    Ring,
    global_recorder,
)
from ray_tpu.observability.profiling import (  # noqa: F401
    Profiler,
    global_profiler,
    profile,
    timeline,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "get_metric", "prometheus_text",
    "start_metrics_server", "EventLog", "Severity", "emit",
    "DashboardHead", "FlightRecorder", "Ring", "global_recorder",
    "global_event_log", "Profiler", "global_profiler", "profile",
    "timeline",
]
