"""Profile events → Chrome trace timeline.

Reference: core_worker/profiling.{h,cc} buffers span events per worker,
flushed to the GCS profile table; ``ray timeline`` (python/ray/state.py:
239 profile_table → chrome_tracing_dump) renders chrome://tracing JSON.
Here spans go to a process-global buffer; ``timeline()`` dumps the same
Chrome trace-event format.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional


class Profiler:
    def __init__(self):
        self._events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    @contextmanager
    def profile(self, event_type: str, extra_data: Optional[dict] = None):
        start = time.perf_counter()
        wall_start = time.time()
        try:
            yield
        finally:
            dur_us = (time.perf_counter() - start) * 1e6
            with self._lock:
                self._events.append({
                    "cat": event_type,
                    "name": event_type,
                    "ph": "X",                      # complete event
                    "ts": wall_start * 1e6,         # microseconds
                    "dur": dur_us,
                    "pid": os.getpid(),
                    "tid": threading.get_ident() % 100_000,
                    "args": extra_data or {},
                })

    def add_instant(self, name: str, extra_data: Optional[dict] = None
                    ) -> None:
        with self._lock:
            self._events.append({
                "cat": "instant", "name": name, "ph": "i",
                "ts": time.time() * 1e6, "s": "g",
                "pid": os.getpid(),
                "tid": threading.get_ident() % 100_000,
                "args": extra_data or {},
            })

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def chrome_trace(self) -> List[Dict[str, Any]]:
        return self.events()

    def dump(self, filename: str) -> str:
        with open(filename, "w") as f:
            json.dump(self.chrome_trace(), f)
        return filename


global_profiler = Profiler()


def profile(event_type: str, extra_data: Optional[dict] = None):
    """``with profile("task:execute"):`` — the reference's
    worker.profile() surface (_raylet.pyx:1478)."""
    return global_profiler.profile(event_type, extra_data)


def timeline(filename: Optional[str] = None):
    """``ray timeline`` equivalent: Chrome trace JSON (list) or file."""
    if filename is None:
        return global_profiler.chrome_trace()
    return global_profiler.dump(filename)
