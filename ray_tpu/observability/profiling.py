"""Profile events → Chrome trace timeline.

Reference: core_worker/profiling.{h,cc} buffers span events per worker,
flushed to the GCS profile table; ``ray timeline`` (python/ray/state.py:
239 profile_table → chrome_tracing_dump) renders chrome://tracing JSON.
Here spans go to a process-global *bounded* ring (long-running raylets
and workers must not grow without limit — raycheck RC10); evicted
events are counted, not silently lost. ``timeline()`` dumps the same
Chrome trace-event format.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from ray_tpu.observability.flight_recorder import Ring

# Plenty for a timeline window; a busy raylet wraps in minutes, which is
# exactly the flight-recorder contract: keep the recent past, not a log.
_MAX_EVENTS = 65_536


def _enabled() -> bool:
    """``Config.enable_timeline`` master switch (reference:
    RAY_PROFILING): off means spans cost one boolean read and the ring
    stays empty — ``timeline()`` then renders an empty trace."""
    from ray_tpu._private.config import Config

    return Config.instance().enable_timeline


class Profiler:
    def __init__(self, max_events: int = _MAX_EVENTS):
        self._events = Ring(max_events)

    @contextmanager
    def profile(self, event_type: str, extra_data: Optional[dict] = None):
        if not _enabled():
            yield
            return
        start = time.perf_counter()
        wall_start = time.time()
        try:
            yield
        finally:
            dur_us = (time.perf_counter() - start) * 1e6
            self._events.append({
                "cat": event_type,
                "name": event_type,
                "ph": "X",                      # complete event
                "ts": wall_start * 1e6,         # microseconds
                "dur": dur_us,
                "pid": os.getpid(),
                "tid": threading.get_ident() % 100_000,
                "args": extra_data or {},
            })

    def add_instant(self, name: str, extra_data: Optional[dict] = None
                    ) -> None:
        if not _enabled():
            return
        self._events.append({
            "cat": "instant", "name": name, "ph": "i",
            "ts": time.time() * 1e6, "s": "g",
            "pid": os.getpid(),
            "tid": threading.get_ident() % 100_000,
            "args": extra_data or {},
        })

    def events(self) -> List[Dict[str, Any]]:
        events, _ = self._events.snapshot()
        return events

    @property
    def dropped(self) -> int:
        """Events evicted from the ring since the last clear()."""
        return self._events.dropped

    def clear(self) -> None:
        self._events.clear()

    def chrome_trace(self) -> List[Dict[str, Any]]:
        return self.events()

    def dump(self, filename: str) -> str:
        with open(filename, "w") as f:
            json.dump(self.chrome_trace(), f)
        return filename


global_profiler = Profiler()


def profile(event_type: str, extra_data: Optional[dict] = None):
    """``with profile("task:execute"):`` — the reference's
    worker.profile() surface (_raylet.pyx:1478)."""
    return global_profiler.profile(event_type, extra_data)


def timeline(filename: Optional[str] = None):
    """``ray timeline`` equivalent: Chrome trace JSON (list) or file."""
    if filename is None:
        return global_profiler.chrome_trace()
    return global_profiler.dump(filename)
