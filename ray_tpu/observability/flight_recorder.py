"""Per-process flight recorder: a bounded ring of recent spans/events.

Reference: Ray's per-worker profile-event buffer flushed to the GCS
profile table (core_worker/profiling.{h,cc}) and the ``ray timeline``
collector (python/ray/state.py chrome_tracing_dump). Here every process
keeps the *last N* spans and events in a bounded ring (a black box, not
a full log) and dumps them to JSONL when something goes wrong — on an
uncaught exception, on SIGUSR2, or on a FATAL event — so a crash
leaves behind the timeline that led up to it. The GCS `collect_timeline`
wire method pulls the same rings live from every node for
``cli.py timeline``.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple


class Ring:
    """Thread-safe bounded ring buffer that counts what it evicts.

    ``deque(maxlen=...)`` silently discards from the head on overflow;
    the ring keeps a ``dropped`` counter so dumps are honest about how
    much history was lost (raycheck RC10: no unbounded deques).
    """

    def __init__(self, capacity: int):
        self._dq: deque = deque(maxlen=max(1, int(capacity)))
        self._dropped = 0
        self._lock = threading.Lock()

    def append(self, item: Any) -> None:
        with self._lock:
            if len(self._dq) == self._dq.maxlen:
                self._dropped += 1
            self._dq.append(item)

    def snapshot(self) -> Tuple[List[Any], int]:
        with self._lock:
            return list(self._dq), self._dropped

    def clear(self) -> None:
        with self._lock:
            self._dq.clear()
            self._dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped


class FlightRecorder:
    """Bounded recorder of recent spans + events with crash-dump hooks."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            from ray_tpu._private.config import Config
            capacity = Config.instance().flight_recorder_capacity
        self._spans = Ring(capacity)
        self._events = Ring(capacity)
        self._clock_offset_s = 0.0
        self._installed = False
        self._prev_excepthook = None

    # ------------------------------------------------------------- feed
    def record_span(self, span: Dict[str, Any]) -> None:
        self._spans.append(span)

    def record_event(self, event: Dict[str, Any]) -> None:
        self._events.append(event)

    # ------------------------------------------------- clock correlation
    def set_clock_offset(self, offset_s: float) -> None:
        """GCS wall clock minus local wall clock, measured over the
        heartbeat RTT (raylet_server._heartbeat_loop); lets the
        timeline merger put every node on one clock."""
        self._clock_offset_s = float(offset_s)

    @property
    def clock_offset_s(self) -> float:
        return self._clock_offset_s

    # ------------------------------------------------------------- read
    def snapshot(self) -> Dict[str, Any]:
        spans, spans_dropped = self._spans.snapshot()
        events, events_dropped = self._events.snapshot()
        from ray_tpu.cluster import fault_plane
        return {
            "pid": os.getpid(),
            "role": fault_plane.process_role(),
            "spans": spans,
            "events": events,
            "dropped": spans_dropped + events_dropped,
            "clock_offset_s": self._clock_offset_s,
            # raycheck: disable=RC02 — wall-clock timestamp for
            # cross-process correlation, not deadline arithmetic
            "wall_time": time.time(),
        }

    def clear(self) -> None:
        self._spans.clear()
        self._events.clear()

    # ------------------------------------------------------------- dump
    def dump(self, path: Optional[str] = None, reason: str = "manual"
             ) -> str:
        """Write the ring contents as JSON-lines; returns the path."""
        snap = self.snapshot()
        if path is None:
            path = os.path.join(
                os.environ.get("TMPDIR", "/tmp"),
                f"ray_tpu_flight_{snap['role']}_{snap['pid']}.jsonl")
        header = {
            "kind": "flight_recorder_dump", "reason": reason,
            "pid": snap["pid"], "role": snap["role"],
            "dropped": snap["dropped"],
            "clock_offset_s": snap["clock_offset_s"],
            "wall_time": snap["wall_time"],
        }
        with open(path, "w") as f:
            f.write(json.dumps(header, default=str) + "\n")
            for span in snap["spans"]:
                f.write(json.dumps({"kind": "span", **span}, default=str)
                        + "\n")
            for event in snap["events"]:
                f.write(json.dumps({"kind": "event", **event},
                                   default=str) + "\n")
        try:
            from ray_tpu.observability import metrics
            metrics.flight_recorder_dumps.inc(
                tags={"reason": reason.split(":", 1)[0]})
        except Exception:
            pass
        return path

    # ------------------------------------------------------------ hooks
    def install(self) -> None:
        """Arm the crash hooks: SIGUSR2 → dump, uncaught exception →
        dump (chained to the previous excepthook). Idempotent; the
        signal handler only installs from the main thread."""
        if self._installed:
            return
        self._installed = True

        def _on_sigusr2(signum, frame):
            try:
                self.dump(reason="SIGUSR2")
            except Exception:
                pass

        try:
            signal.signal(signal.SIGUSR2, _on_sigusr2)
        except (ValueError, OSError):
            pass  # not the main thread / platform without SIGUSR2

        self._prev_excepthook = sys.excepthook

        def _on_uncaught(exc_type, exc, tb):
            try:
                self.dump(reason=f"uncaught:{exc_type.__name__}")
            except Exception:
                pass
            if self._prev_excepthook is not None:
                self._prev_excepthook(exc_type, exc, tb)

        sys.excepthook = _on_uncaught


def merge_chrome_trace(dumps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-node flight-recorder snapshots into one chrome://tracing
    document.

    Each dump carries ``clock_offset_s`` = GCS wall clock minus the
    node's local wall clock (measured over heartbeat RTT), so every
    span's timestamps are shifted onto the GCS reference clock before
    merging — one consistent time axis across the whole cluster.
    Unreachable nodes (dumps with an ``error`` key) become zero-length
    processes so the viewer still shows they were asked.
    """
    trace_events: List[Dict[str, Any]] = []
    for pid, dump in enumerate(dumps):
        node = str(dump.get("node_id", dump.get("role", "?")))[:16]
        role = dump.get("role", "?")
        label = (f"{node} [{role}] UNREACHABLE: {dump['error']}"
                 if "error" in dump else f"{node} [{role}]")
        trace_events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": label},
        })
        # one named lane per live background thread, labelled by its
        # root function (threads.root_label) — the same naming
        # raycheck's RC16/RC17 data-race reports use, so a report and
        # a timeline lane identify a thread identically
        roots = dump.get("thread_roots") or {}
        for tid, tname in enumerate(sorted(roots), start=1):
            trace_events.append({
                "ph": "M", "name": "thread_name", "pid": pid,
                "tid": tid,
                "args": {"name": f"{tname} ({roots[tname]})"},
            })
        offset_us = float(dump.get("clock_offset_s") or 0.0) * 1e6
        for span in dump.get("spans") or []:
            start = span.get("start_time")
            if start is None:
                continue
            end = span.get("end_time") or start
            trace_events.append({
                "ph": "X", "name": span.get("name", "?"),
                "cat": span.get("status", "OK"),
                "pid": pid, "tid": 0,
                "ts": start * 1e6 + offset_us,
                "dur": max(0.0, (end - start) * 1e6),
                "args": {
                    "trace_id": span.get("trace_id"),
                    "span_id": span.get("span_id"),
                    "parent_id": span.get("parent_id"),
                    **(span.get("attributes") or {}),
                },
            })
        for event in dump.get("events") or []:
            ts = event.get("timestamp", event.get("time"))
            if ts is None:
                continue
            trace_events.append({
                "ph": "i", "name": event.get("name",
                                             event.get("kind", "event")),
                "pid": pid, "tid": 0, "s": "p",
                "ts": float(ts) * 1e6 + offset_us,
                "args": {k: v for k, v in event.items()
                         if k not in ("name", "timestamp", "time")},
            })
    return {"traceEvents": trace_events,
            "displayTimeUnit": "ms",
            "metadata": {"nodes": len(dumps)}}


global_recorder = FlightRecorder()


def install() -> None:
    """Arm the process's crash-dump hooks when the plane is enabled
    (called from gcs_server/raylet_server main() and Runtime init)."""
    from ray_tpu._private.config import Config
    if Config.instance().observability_plane_enabled:
        global_recorder.install()


def record_fatal(event: Dict[str, Any]) -> None:
    """FATAL-severity hook (observability.events.emit): record the
    event, then dump the black box while the process can still write."""
    global_recorder.record_event(event)
    try:
        global_recorder.dump(reason="fatal_event")
    except Exception:
        pass
