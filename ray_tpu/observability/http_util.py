"""Shared JSON-over-HTTP plumbing for the dashboards.

One route-table server used by both the in-process dashboard
(observability/dashboard.py) and the process-tier head
(observability/dashboard_head.py): unknown path -> 404, handler
exception -> 500 with an error JSON, everything else -> 200.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Tuple
from urllib.parse import parse_qs, urlparse

Route = Callable[[Dict], Tuple[bytes, str]]  # query -> (body, ctype)


def start_json_server(routes: Dict[str, Route], host: str = "127.0.0.1",
                      port: int = 0) -> ThreadingHTTPServer:
    """Serve a route table on a daemon thread. Caller owns shutdown():
    server.shutdown(); server.server_close()."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def do_GET(self):
            parsed = urlparse(self.path)
            path = parsed.path.rstrip("/") or "/"
            fn = routes.get(path)
            if fn is None:
                self.send_response(404)
                self.end_headers()
                return
            try:
                body, ctype = fn(parse_qs(parsed.query))
                code = 200
            except Exception as e:  # noqa: BLE001 — surface as 500
                body = json.dumps({"error": repr(e)}).encode()
                ctype = "application/json"
                code = 500
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(
        target=server.serve_forever, daemon=True,
        name=f"json-http-{server.server_address[1]}").start()
    return server
