"""Process-tier dashboard head.

Reference: dashboard/head.py aggregating per-node agents
(dashboard/agent.py) — here each raylet process doubles as its node's
agent (`node_stats` carries reporter-style process stats), and the head
is an HTTP server over the GCS view, per-node agent polls, the actor
table, and a ring buffer of the pubsub LOG channel.

Routes (JSON):
  /             — single-page web UI (text/html; observability/web_ui.py)
  /api/cluster  — GCS cluster view
  /api/nodes    — per-node stats incl. agent process stats
  /api/actors   — GCS actor table
  /api/logs     — recent worker log lines (?n= to bound)
  /api/jobs     — job submission table
  /healthz      — liveness probe
"""

from __future__ import annotations

import json
import logging
import threading
from collections import deque
from typing import Dict, Optional

logger = logging.getLogger(__name__)


class DashboardHead:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1",
                 port: int = 0, log_buffer: int = 5000):
        from ray_tpu.cluster.rpc import ReconnectingRpcClient
        from ray_tpu.observability.http_util import start_json_server

        self.gcs_address = gcs_address
        self._gcs = ReconnectingRpcClient(gcs_address)
        self._raylet_clients: Dict[str, object] = {}
        self._raylet_lock = threading.Lock()
        self._logs: deque = deque(maxlen=log_buffer)
        self._subscriber = None

        def as_json(fn):
            return lambda query: (json.dumps(fn(query)).encode(),
                                  "application/json")

        from ray_tpu.observability.web_ui import INDEX_HTML

        routes = {
            "/": lambda q: (INDEX_HTML.encode(), "text/html"),
            "/healthz": as_json(lambda q: {"ok": True}),
            "/api/cluster": as_json(
                lambda q: self._gcs.call("cluster_view", timeout=10.0)),
            "/api/nodes": as_json(lambda q: self._nodes()),
            "/api/actors": as_json(
                lambda q: self._gcs.call("actor_list", timeout=10.0)),
            "/api/logs": as_json(self._recent_logs),
            "/api/jobs": as_json(lambda q: self._jobs()),
        }
        # bind the HTTP server BEFORE subscribing: a bind failure must
        # not leak a live poll thread with no handle to stop it
        self._server = start_json_server(routes, host, port)
        self.host, self.port = self._server.server_address
        try:
            self._start_log_subscriber()
        except Exception:
            self._server.shutdown()
            self._server.server_close()
            raise

    # ------------------------------------------------------------- plumbing
    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _start_log_subscriber(self) -> None:
        from ray_tpu.pubsub import LOG_CHANNEL, Subscriber

        def on_log(channel, node_id, message):
            for entry in message.get("batch", ()):
                self._logs.append({"node_id": node_id, **entry})

        self._subscriber = Subscriber(
            f"dashboard-{id(self):x}",
            poll_fn=lambda subscriber_id, timeout: self._gcs.call(
                "pubsub_poll", subscriber_id=subscriber_id,
                timeout_s=timeout, timeout=timeout + 10.0),
            subscribe_fn=lambda **kw: self._gcs.call(
                "pubsub_subscribe", timeout=10.0, **kw),
            unsubscribe_fn=lambda **kw: self._gcs.call(
                "pubsub_unsubscribe", timeout=10.0, **kw),
            poll_timeout_s=2.0)
        self._subscriber.subscribe(LOG_CHANNEL, None, on_log)

    def _raylet(self, address: str):
        from ray_tpu.cluster.rpc import RpcClient

        with self._raylet_lock:
            c = self._raylet_clients.get(address)
            if c is None or c.closed:
                c = RpcClient(address)
                self._raylet_clients[address] = c
            return c

    # --------------------------------------------------------------- routes
    def _jobs(self) -> list:
        """Submitted jobs from the GCS KV (reference: dashboard job
        module listing)."""
        from ray_tpu.cluster.job_manager import JOB_NS, list_job_rows

        return list_job_rows(
            lambda prefix: self._gcs.call("kv_keys", ns=JOB_NS,
                                          prefix=prefix, timeout=10.0),
            lambda key: self._gcs.call("kv_get", ns=JOB_NS, key=key,
                                       timeout=10.0))

    def _recent_logs(self, query: Dict) -> list:
        n = int(query.get("n", ["100"])[0])
        return list(self._logs)[-n:] if n > 0 else []

    def _nodes(self) -> list:
        view = self._gcs.call("cluster_view", timeout=10.0)
        rows = []
        calls = []
        for node_id, info in view["nodes"].items():
            row = {"node_id": node_id, "alive": info["alive"],
                   "address": info["address"]}
            call = None
            if info["alive"]:
                try:
                    # fan the polls out; one wedged node must cost the
                    # endpoint max(latency), not sum (reference:
                    # dashboard head polls agents concurrently)
                    call = self._raylet(info["address"]).call_async(
                        "node_stats")
                except Exception as e:  # noqa: BLE001 — node mid-death
                    row["stats_error"] = repr(e)
            rows.append(row)
            calls.append(call)
        for row, call in zip(rows, calls):
            if call is None:
                continue
            try:
                row.update(call.result(timeout=10.0))
            except Exception as e:  # noqa: BLE001
                row["stats_error"] = repr(e)
        return rows

    def stop(self) -> None:
        if self._subscriber is not None:
            self._subscriber.close()
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        self._gcs.close()
        with self._raylet_lock:
            clients = list(self._raylet_clients.values())
        for c in clients:
            c.close()


def main(argv=None) -> None:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    head = DashboardHead(args.gcs, args.host, args.port)
    print(f"DASHBOARD_URL {head.url}", flush=True)
    threading.Event().wait()


if __name__ == "__main__":
    main()
