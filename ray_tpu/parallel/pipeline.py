"""Pipeline parallelism over the ``pp`` mesh axis.

Net-new relative to the reference (SURVEY.md §2.3: no pipeline-parallel
trainer exists there). GPipe-style schedule expressed the TPU way: one
SPMD program under shard_map where every pipeline stage runs the same
code on its own layer shard, and activations rotate stage-to-stage with
``ppermute`` inside a ``lax.scan`` — no per-stage processes, no p2p
sockets. Backward works through plain ``jax.grad``: the transpose of
ppermute is the reverse rotation, so the 1B1F backward schedule falls out
of autodiff.

The schedule runs ``num_microbatches + pp - 1`` ticks; each tick every
stage processes the microbatch it holds (bubbles at the edges process
garbage that is masked out of the loss by the caller taking only valid
outputs — standard GPipe bubble accounting).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_spmd(stage_fn: Callable, params, x: jax.Array,
                  axis_name: str = "pp", num_microbatches: int = None):
    """Run ``stage_fn(params, microbatch) -> microbatch`` as a pipeline.

    Called inside shard_map where:
      - ``params`` is the local stage's layer stack (layers axis sharded
        over ``axis_name``),
      - ``x`` is the local batch shard [B, ...]; B must divide into
        ``num_microbatches`` equal microbatches.

    Every stage feeds its output to the next ring neighbor; stage 0
    injects fresh microbatches and the last stage's outputs are collected.
    Returns [B, ...] outputs valid on the LAST stage (callers psum or
    gather as needed; see models/transformer.py which broadcasts the loss).
    """
    pp = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    if num_microbatches is None:
        num_microbatches = pp
    mb = x.shape[0] // num_microbatches
    micro = x.reshape(num_microbatches, mb, *x.shape[1:])
    total_ticks = num_microbatches + pp - 1

    perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]

    def tick(carry, t):
        state, outputs = carry
        # stage 0 injects microbatch t (or garbage past the end)
        inject = micro[jnp.minimum(t, num_microbatches - 1)]
        current = jnp.where(stage == 0, inject, state)
        processed = stage_fn(params, current)
        # last stage records its finished microbatch at slot t - (pp - 1)
        out_slot = t - (pp - 1)
        is_valid = (stage == pp - 1) & (out_slot >= 0)
        outputs = lax.cond(
            is_valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, processed, jnp.maximum(out_slot, 0), 0),
            lambda o: o,
            outputs,
        )
        # rotate activations to the next stage
        state = lax.ppermute(processed, axis_name, perm_fwd)
        return (state, outputs), None

    from ray_tpu.parallel import pvary

    state0 = pvary(jnp.zeros_like(micro[0]), axis_name)
    outputs0 = pvary(jnp.zeros_like(micro), axis_name)
    (state, outputs), _ = lax.scan(
        tick, (state0, outputs0), jnp.arange(total_ticks))
    # only the last stage recorded real outputs; masked psum broadcasts
    # them ring-wide so the result is replicated over the pp axis
    outputs = lax.psum(jnp.where(stage == pp - 1, outputs, 0.0), axis_name)
    return outputs.reshape(x.shape[0], *x.shape[1:])
