"""Ring attention: exact attention over sequences sharded across devices.

Net-new relative to the reference (SURVEY.md §2.3: no sequence/context
parallelism exists there); this is the TPU-native long-context path built
on the collective layer instead of a port.

Each device on the ``sp`` ring holds a query/key/value shard of the
sequence. The kernel loops ``sp`` steps: compute blockwise attention of
the local Q against the currently-held KV shard with a running
log-sum-exp (flash-attention style numerically-stable accumulation), then
``ppermute`` the KV shard to the next ring neighbor so communication
overlaps the arithmetic. After sp steps every Q block has attended to the
full sequence without any device ever materializing it.

Causal masking works on global positions: shard s of the sequence owns
positions [s*chunk, (s+1)*chunk), and each step masks by comparing global
q/k indices.

Usage: wrap in shard_map with sequence axis sharded over "sp"; see
ray_tpu/models/transformer.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _block_attention(q, k, v, bias, q_offset, k_offset, causal, sm_scale):
    """One Q-shard x KV-shard block: returns (unnormalized_out, m, l).

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]
    m: running max [B, H, Sq]; l: running denominator [B, H, Sq]
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sm_scale
    if bias is not None:
        logits = logits + bias
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(sq)[:, None]
        k_pos = k_offset + jnp.arange(sk)[None, :]
        mask = q_pos >= k_pos
        logits = jnp.where(mask[None, None, :, :], logits, -jnp.inf)
    m = jnp.max(logits, axis=-1)  # [B, H, Sq]
    # guard fully-masked rows
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(logits - m_safe[..., None])
    p = jnp.where(jnp.isfinite(logits), p, 0.0)
    l = jnp.sum(p, axis=-1)  # noqa: E741
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return out, m_safe, l, jnp.isfinite(m)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "sp", causal: bool = True,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Exact attention with KV rotating around the ``axis_name`` ring.

    Must be called inside a shard_map region where q/k/v carry the local
    sequence shard: [B, S_local, H, D]. Returns [B, S_local, H, D].
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_local = q.shape[1]
    q_offset = my * s_local

    b, _, h, d = q.shape
    # scan carries must match the device-varying set of the loop body,
    # which spans every manual axis in scope (sp plus any enclosing dp/tp
    # manual axes) — deriving the zeros from q inherits exactly that set
    qf = q.astype(jnp.float32)
    acc = jnp.zeros_like(qf)
    base = jnp.transpose(qf.sum(-1), (0, 2, 1)) * 0.0  # [B, H, S_local]
    m_run = base - jnp.inf
    l_run = base

    def step(carry, i):
        acc, m_run, l_run, k_cur, v_cur = carry
        # KV shard currently held came from ring position (my - i) mod n
        src = (my - i) % n
        k_offset = src * s_local
        out, m_new, l_new, valid = _block_attention(
            q.astype(jnp.float32), k_cur.astype(jnp.float32),
            v_cur.astype(jnp.float32), None, q_offset, k_offset, causal,
            sm_scale)
        m_new = jnp.where(valid, m_new, -jnp.inf)
        # merge running softmax statistics (flash-attention update)
        m_tot = jnp.maximum(m_run, m_new)
        m_tot_safe = jnp.where(jnp.isfinite(m_tot), m_tot, 0.0)
        alpha = jnp.where(jnp.isfinite(m_run),
                          jnp.exp(m_run - m_tot_safe), 0.0)
        beta = jnp.where(jnp.isfinite(m_new),
                         jnp.exp(m_new - m_tot_safe), 0.0)
        l_tot = alpha * l_run + beta * l_new
        acc = (acc * jnp.transpose(alpha, (0, 2, 1))[..., None]
               + out * jnp.transpose(beta, (0, 2, 1))[..., None])
        # rotate KV to the next neighbor; the last rotation is wasted but
        # keeps the loop body uniform for the compiler
        perm = [(r, (r + 1) % n) for r in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (acc, m_tot, l_tot, k_nxt, v_nxt), None

    (acc, m_run, l_run, _, _), _ = lax.scan(
        step, (acc, m_run, l_run, k, v), jnp.arange(n))
    denom = jnp.transpose(jnp.maximum(l_run, 1e-20), (0, 2, 1))[..., None]
    return (acc / denom).astype(q.dtype)


def local_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    sm_scale: Optional[float] = None) -> jax.Array:
    """Single-device reference attention with identical semantics; used
    as the sp=1 fast path and the correctness oracle in tests."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    out, m, l, _ = _block_attention(  # noqa: E741
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32), None, 0, 0, causal, sm_scale)
    denom = jnp.transpose(jnp.maximum(l, 1e-20), (0, 2, 1))[..., None]
    return (out / denom).astype(q.dtype)
