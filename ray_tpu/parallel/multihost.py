"""Multi-host bring-up: DCN x ICI meshes and process-group init.

The reference scales across machines with NCCL/MPI process groups
bootstrapped through a named-actor rendezvous
(util/collective/collective_group/nccl_collective_group.py:28-100).
The TPU equivalent is jax.distributed: every host process joins a
coordinator, jax.devices() becomes the global device set, and XLA
routes collectives over ICI within a slice and DCN between slices.

Mesh layout rule (scaling-book recipe): put the axis with the least
communication volume per step (dp, then pp) on DCN — outermost in the
device mesh — and keep tensor/sequence-parallel axes on ICI.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """jax.distributed.initialize with env auto-detection; no-op (False)
    for single-process runs so the same script works 1-host and N-host
    (reference parity: collective.init_collective_group's rendezvous)."""
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "RAY_TPU_COORDINATOR")
    if num_processes is None:
        env = os.environ.get("RAY_TPU_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("RAY_TPU_PROCESS_ID")
        process_id = int(env) if env else None
    if coordinator_address is None and num_processes in (None, 1):
        return False  # single host, nothing to join
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    _initialized = True
    return True


def multihost_mesh(axes: Dict[str, int],
                   dcn_axes: Optional[Sequence[str]] = None):
    """Build a Mesh whose listed `dcn_axes` (default: the leading axis)
    span hosts over DCN while the rest stay on in-slice ICI.

    axes: ordered {name: size}; product must equal the global device
    count. Single-host (or CPU) runs fall back to a plain device mesh
    with identical axis names, so tests and dry runs share the code
    path."""
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    names = list(axes.keys())
    sizes = [axes[n] for n in names]
    total = int(np.prod(sizes))
    n_devices = len(jax.devices())
    if total != n_devices:
        raise ValueError(
            f"mesh axes {axes} need {total} devices, have {n_devices}")
    if dcn_axes is None:
        dcn_axes = names[:1]
    num_slices = getattr(jax.devices()[0], "slice_index", None)
    multi_slice = (num_slices is not None and
                   len({d.slice_index for d in jax.devices()}) > 1)
    if multi_slice:
        dcn_shape = [axes[n] if n in dcn_axes else 1 for n in names]
        ici_shape = [1 if n in dcn_axes else axes[n] for n in names]
        devices = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape)
    else:
        devices = mesh_utils.create_device_mesh(sizes)
    return Mesh(devices, tuple(names))


def process_count() -> int:
    import jax

    return jax.process_count()


def process_index() -> int:
    import jax

    return jax.process_index()


def sync_global_devices(name: str = "barrier") -> None:
    """Cross-host barrier: one tiny psum over every device (reference:
    collective.barrier)."""
    import jax
    import jax.numpy as jnp

    jax.block_until_ready(
        jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
            jnp.ones((len(jax.local_devices()),))))
    logger.debug("sync_global_devices(%s) complete", name)
