"""Ulysses-style sequence parallelism: all-to-all head/sequence swap.

The second of the two long-context strategies (with
parallel/ring_attention.py). Ring attention keeps sequence shards fixed
and rotates K/V blocks around the ICI ring — O(1) memory overhead,
latency hidden behind compute. Ulysses (DeepSpeed-Ulysses,
arXiv:2309.14509) instead swaps the sharding: an all-to-all re-shards
[batch, seq/N, heads, dim] into [batch, seq, heads/N, dim], runs plain
(flash) attention on full sequences for a head subset, and swaps back.
Two all-to-alls per attention call, but the attention itself is local —
the better trade when heads >> devices and ICI all-to-all bandwidth is
plentiful (TPU's torus excels at this).

Use inside shard_map over the sequence axis, like ring_attention:

    out = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp"),
        mesh=mesh, in_specs=P(None, "sp", None, None), ...)
"""

from __future__ import annotations

from typing import Optional

import jax

from ray_tpu.ops.attention import flash_attention


def _heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """[b, s/N, h, d] -> [b, s, h/N, d]: split heads across the axis,
    gather the sequence. One ICI all-to-all."""
    return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def _seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """[b, s, h/N, d] -> [b, s/N, h, d]: the inverse swap."""
    return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, causal: bool = True,
                      sm_scale: Optional[float] = None) -> jax.Array:
    """Attention over sequence-sharded q/k/v ([batch, seq_local, heads,
    head_dim], same layout as ring_attention). heads must divide by the
    axis size."""
    sp = jax.lax.psum(1, axis_name)
    heads = q.shape[2]
    if heads % sp != 0:
        raise ValueError(
            f"ulysses needs heads ({heads}) divisible by the sequence-"
            f"parallel axis size ({sp})")
    q_h = _heads_to_seq(q, axis_name)
    k_h = _heads_to_seq(k, axis_name)
    v_h = _heads_to_seq(v, axis_name)
    out = flash_attention(q_h, k_h, v_h, causal=causal, sm_scale=sm_scale)
    return _seq_to_heads(out, axis_name)
