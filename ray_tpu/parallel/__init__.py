"""ray_tpu.parallel — mesh, sharding, and parallelism primitives."""

import jax
from jax import lax as _lax


def pvary(x, axis_names):
    """Mark a constant as device-varying over mesh axes (needed for
    shard_map scan carries). Wraps the pcast/pvary API shift."""
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    if hasattr(_lax, "pcast"):
        return _lax.pcast(x, tuple(axis_names), to="varying")
    return _lax.pvary(x, tuple(axis_names))


from ray_tpu.parallel.mesh import (  # noqa: F401,E402
    AXIS_ORDER,
    DEFAULT_RULES,
    MeshSpec,
    build_mesh,
    fsdp_rules,
    sharding_for,
    spec_for,
)
from ray_tpu.parallel.pipeline import pipeline_spmd  # noqa: F401,E402
from ray_tpu.parallel.ring_attention import (  # noqa: F401,E402
    local_attention,
    ring_attention,
)
