"""Device mesh management for multi-axis parallelism.

The TPU-native replacement for the reference's process-group plumbing
(util/collective group bootstrap, train/backend.py worker-group wiring):
instead of N processes rendezvousing NCCL communicators, a single SPMD
program runs over a `jax.sharding.Mesh` whose named axes carry the
parallelism kinds:

  dp  data parallelism (batch sharding; FSDP rides this axis too)
  pp  pipeline parallelism (layer stages)
  sp  sequence/context parallelism (ring attention over ICI neighbors)
  tp  tensor parallelism (heads / hidden sharding)

Expert parallelism (ep) rides the dp axis (GShard/Switch convention:
experts distributed over data-parallel ranks), so a 4-axis mesh covers all
five strategies. Axis sizes multiply to the device count; size-1 axes are
legal and compile away.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER = ("dp", "pp", "sp", "tp")

# Canonical logical-axis -> mesh-axis rules for transformer state.
# (the moral equivalent of the reference's per-backend device placement,
# but declarative; see models/transformer.py for use)
DEFAULT_RULES: Dict[str, Optional[str]] = {
    "batch": "dp",
    "seq": "sp",
    "heads": "tp",
    "kv_heads": "tp",
    "hidden": None,
    "mlp": "tp",
    "vocab": "tp",
    "layers": "pp",
    "experts": "dp",   # expert parallelism over the dp axis
    "stage": "pp",
}


@dataclass(frozen=True)
class MeshSpec:
    """A named factorization of the device count."""

    dp: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.pp * self.sp * self.tp

    def axis_sizes(self) -> Tuple[int, ...]:
        return (self.dp, self.pp, self.sp, self.tp)

    @classmethod
    def auto(cls, n_devices: int, *, want_pp: bool = True,
             want_sp: bool = True, want_tp: bool = True) -> "MeshSpec":
        """Greedy factorization: give tp, then sp, then pp a factor of 2
        each (ICI-neighbor axes first), remainder to dp."""
        remaining = n_devices
        tp = 2 if want_tp and remaining % 2 == 0 and remaining >= 2 else 1
        remaining //= tp
        sp = 2 if want_sp and remaining % 2 == 0 and remaining >= 2 else 1
        remaining //= sp
        pp = 2 if want_pp and remaining % 2 == 0 and remaining >= 2 else 1
        remaining //= pp
        return cls(dp=remaining, pp=pp, sp=sp, tp=tp)


def build_mesh(spec: MeshSpec, devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < spec.size:
        raise ValueError(
            f"mesh needs {spec.size} devices, have {len(devices)}")
    arr = np.array(devices[: spec.size]).reshape(spec.axis_sizes())
    return Mesh(arr, AXIS_ORDER)


def spec_for(logical_axes: Sequence[Optional[str]],
             rules: Optional[Dict[str, Optional[str]]] = None) -> P:
    """Map logical array axes to a PartitionSpec through the rule table."""
    rules = rules or DEFAULT_RULES
    parts = []
    for ax in logical_axes:
        if ax is None:
            parts.append(None)
        else:
            parts.append(rules.get(ax))
    return P(*parts)


def sharding_for(mesh: Mesh, logical_axes: Sequence[Optional[str]],
                 rules: Optional[Dict[str, Optional[str]]] = None
                 ) -> NamedSharding:
    return NamedSharding(mesh, spec_for(logical_axes, rules))


def fsdp_rules(rules: Optional[Dict[str, Optional[str]]] = None
               ) -> Dict[str, Optional[str]]:
    """Variant rule table that additionally shards parameters' hidden axis
    over dp — fully-sharded data parallelism."""
    out = dict(rules or DEFAULT_RULES)
    out["hidden"] = "dp"
    return out
