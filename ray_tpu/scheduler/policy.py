"""Scheduling policies: where does a resource request run?

Two implementations behind one seam (the reference gates policies behind
SchedulingPolicy, src/ray/raylet/scheduling/scheduling_policy.h:26):

1. ``HybridPolicy`` — an exact re-implementation of the reference's hybrid
   packing/round-robin policy (scheduling_policy.cc:39-150): skip
   infeasible nodes, prefer available ones, tie-break by critical-resource
   utilization *truncated to zero below the spread threshold* so light
   nodes compare equal and the lowest node id wins (packing); above the
   threshold the minimum-utilization node wins (spreading). Scans
   sequentially, updating availability after each placement.

2. ``BatchedHybridPolicy`` — the TPU-first path: pending requests are
   grouped by scheduling class, and each class's placement over the whole
   ``[nodes x resources]`` matrix is computed as one vectorized
   water-filling solve (feasibility mask -> per-node capacity -> ordered
   cumulative fill). One device dispatch schedules thousands of tasks.
   Verified against HybridPolicy on randomized instances in
   tests/test_scheduling_policy.py.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu._private.config import Config

_BIG = np.int64(2**62)


@dataclass
class SchedulingOptions:
    spread_threshold: float = 0.5
    # If set, only this node may be chosen (NodeAffinity strategy).
    node_affinity_slot: Optional[int] = None
    node_affinity_soft: bool = False
    # SPREAD strategy: ignore packing, round-robin over feasible nodes.
    spread_strategy: bool = False
    # Do not consider nodes where the request is merely feasible but not
    # currently available (used for actor creation bursts).
    require_available: bool = False

    @classmethod
    def default(cls) -> "SchedulingOptions":
        return cls(spread_threshold=Config.instance().scheduler_spread_threshold)


class HybridPolicy:
    """Exact sequential re-implementation of the reference hybrid policy."""

    def schedule_one(
        self,
        req: np.ndarray,            # [R] int64 fixed-point demand
        total: np.ndarray,          # [N, R]
        available: np.ndarray,      # [N, R]
        alive: np.ndarray,          # [N] bool
        local_slot: int,
        opts: SchedulingOptions,
    ) -> int:
        """Return the chosen node slot, or -1 if infeasible everywhere.

        Does NOT mutate availability; callers allocate on the chosen node.
        """
        n = total.shape[0]
        if n == 0:
            return -1
        if opts.node_affinity_slot is not None:
            s = opts.node_affinity_slot
            feasible = alive[s] and bool(np.all(total[s] >= req))
            if feasible:
                return s
            if not opts.node_affinity_soft:
                return -1

        feasible = alive & np.all(total >= req, axis=1)
        if not feasible.any():
            return -1
        avail_mask = feasible & np.all(available >= req, axis=1)

        # Critical-resource utilization per node *after* hypothetically
        # placing the request (reference scores on current usage;
        # scheduling_policy.cc:41-57 uses current used/total).
        with np.errstate(divide="ignore", invalid="ignore"):
            util = np.where(
                total > 0, (total - available) / np.maximum(total, 1), 0.0
            ).max(axis=1)

        if opts.spread_strategy:
            candidates = np.flatnonzero(avail_mask if avail_mask.any() else feasible)
            # Round-robin handled by the caller advancing an index; here we
            # pick min utilization then lowest id.
            order = sorted(candidates, key=lambda s: (util[s], s))
            return int(order[0])

        def best_among(mask: np.ndarray) -> int:
            slots = np.flatnonzero(mask)
            # Truncate below threshold -> ties -> prefer local, then low id
            # (reference: "prioritize local node" then node id order).
            def keyf(s):
                score = 0.0 if util[s] < opts.spread_threshold else float(util[s])
                is_local = 0 if s == local_slot else 1
                return (score, is_local, s)

            return int(min(slots, key=keyf))

        if avail_mask.any():
            return best_among(avail_mask)
        if opts.require_available:
            return -1
        return best_among(feasible)


class BatchedHybridPolicy:
    """Vectorized scheduling of a *batch* of identical-class requests.

    For one scheduling class with demand vector ``req`` and ``k`` pending
    requests, computes how many land on each node in one shot:

      capacity_n = min_r floor(available[n,r] / req[r])   (vectorized)
      order      = nodes sorted by (truncated utilization, not-local, id)
      fill       = water-filling k requests through `order` by capacity

    Returns per-node counts. The sequential policy would interleave nodes
    once all are above the spread threshold; water-filling instead fills in
    score order, which preserves the pack-below-threshold and
    spread-above-threshold structure while being one fused computation.
    """

    def __init__(self, use_jax: Optional[bool] = None):
        if use_jax is None:
            use_jax = Config.instance().scheduler_use_vectorized_policy
        self._jax_fn = None
        self._jax_fused = None
        self._jax_pipelined = None
        self.use_jax = use_jax

    # ---- numpy reference of the batched solve ---------------------------
    def schedule_class(
        self,
        req: np.ndarray,           # [R]
        k: int,
        total: np.ndarray,         # [N, R]
        available: np.ndarray,     # [N, R]
        alive: np.ndarray,         # [N]
        local_slot: int,
        opts: SchedulingOptions,
    ) -> np.ndarray:
        """Return [N] int64 counts; sum(counts) <= k (rest infeasible)."""
        n = total.shape[0]
        if n == 0 or k <= 0:
            return np.zeros(n, dtype=np.int64)
        feasible = alive & np.all(total >= req, axis=1)
        pos = req > 0
        if pos.any():
            cap = np.where(
                feasible[:, None] & pos[None, :],
                available // np.maximum(req, 1),
                _BIG,
            ).min(axis=1)
            cap = np.where(feasible, np.maximum(cap, 0), 0)
        else:
            cap = np.where(feasible, _BIG, 0)
        with np.errstate(divide="ignore", invalid="ignore"):
            util = np.where(
                total > 0, (total - available) / np.maximum(total, 1), 0.0
            ).max(axis=1)
        trunc = np.where(util < opts.spread_threshold, 0.0, util)
        not_local = (np.arange(n) != local_slot).astype(np.int64)
        order = np.lexsort((np.arange(n), not_local, trunc))
        counts = np.zeros(n, dtype=np.int64)
        remaining = k
        for s in order:
            if remaining <= 0:
                break
            take = int(min(cap[s], remaining))
            counts[s] = take
            remaining -= take
        return counts

    # ---- jax fused version ----------------------------------------------
    # The device kernel runs in float32 (TPU-native; int64 is unavailable
    # under jit without x64). Fixed-point magnitudes up to ~2^24 divide
    # exactly; beyond that a capacity may be off by one, which the host
    # commit loop in schedule_classes detects (allocation would go
    # negative) and repairs with the exact numpy solve for that class.
    _CAP_MAX = 1.0e9

    @staticmethod
    def _device_class_solve(req, k, total, avail, alive, perm1, threshold,
                            cap_max):
        """One scheduling class over the node matrix, on device. All f32.

        The single source of truth for the device solve — used by both the
        per-class jit (schedule_classes) and the fused whole-tick scan
        (schedule_tick_fused), so a fix to one cannot miss the other.
        req: [R]; total/avail: [N, R]; perm1: node order by (is_local, id).
        Returns counts [N] f32.
        """
        import jax.numpy as jnp

        feasible = alive & jnp.all(total >= req[None, :], axis=-1)
        pos = req > 0
        ratio = jnp.where(
            pos[None, :],
            jnp.floor(avail / jnp.maximum(req[None, :], 1.0)),
            cap_max)
        cap = jnp.min(ratio, axis=-1)
        cap = jnp.where(feasible, jnp.clip(cap, 0.0, cap_max), 0.0)
        util = jnp.max(
            jnp.where(total > 0, (total - avail)
                      / jnp.maximum(total, 1.0), 0.0), axis=-1)
        trunc = jnp.where(util < threshold, 0.0, util)
        # exact lexsort (trunc, not_local, id): stable pass over the
        # pre-sorted (not_local, id) order — matches np.lexsort in the
        # host solve bit-for-bit
        order = perm1[jnp.argsort(trunc[perm1], stable=True)]
        cap_sorted = cap[order]
        csum = jnp.cumsum(cap_sorted)
        take_sorted = jnp.clip(k - (csum - cap_sorted), 0.0, cap_sorted)
        return jnp.zeros_like(cap).at[order].set(take_sorted)

    @staticmethod
    def _perm1(n, local_slot):
        import jax.numpy as jnp

        not_local = (jnp.arange(n) != local_slot).astype(jnp.float32)
        return jnp.argsort(not_local, stable=True)

    def _build_jax(self):
        import jax

        cap_max = self._CAP_MAX
        class_solve = self._device_class_solve
        perm1_fn = self._perm1

        def solve(req, k, total, available, alive, local_slot, threshold):
            # req: [R]; total/available: [N, R] (already float32)
            perm1 = perm1_fn(total.shape[0], local_slot)
            counts = class_solve(req, k, total, available, alive, perm1,
                                 threshold, cap_max)
            return counts.astype(jax.numpy.int32)

        return jax.jit(solve)

    def _build_jax_fused(self):
        """Whole-tick kernel: lax.scan over scheduling classes carrying
        availability — one device dispatch schedules the entire pending
        queue. This is the bench.py north-star path."""
        import jax
        import jax.numpy as jnp

        cap_max = self._CAP_MAX
        class_solve = self._device_class_solve
        perm1_fn = self._perm1

        def tick(reqs, ks, total, available, alive, local_slot, threshold):
            # reqs: [C, R]; ks: [C]; total/available: [N, R] (float32)
            perm1 = perm1_fn(total.shape[0], local_slot)

            def one_class(avail, inputs):
                req, k = inputs
                counts = class_solve(req, k, total, avail, alive, perm1,
                                     threshold, cap_max)
                return avail - counts[:, None] * req[None, :], counts

            _, counts = jax.lax.scan(one_class, available, (reqs, ks))
            return counts.astype(jnp.int32)

        return jax.jit(tick)

    def _build_jax_pipelined_step(self):
        """One pipelined drain step: fold last tick's deltas into the
        DEVICE-RESIDENT availability, solve the whole tick, and
        pre-subtract this tick's usage — a single dispatch, no matrix
        re-upload. The availability buffer is DONATED: the update is
        in-place on device, so double-buffered ticks touch the host only
        for the counts pull.

        Inputs: avail [N,R] (device, donated), freed [N,R] (device —
        last tick's usage array, returned by the previous step), delta
        [N,R] (host correction upload; all-zeros and cached on device
        when the previous repair did not clamp), reqs [C,R], ks [C].
        Returns (avail', usage, counts).
        """
        import jax
        import jax.numpy as jnp

        cap_max = self._CAP_MAX
        class_solve = self._device_class_solve
        perm1_fn = self._perm1

        def step(avail, freed, delta, reqs, ks, total, alive, local_slot,
                 threshold):
            avail = avail + freed + delta
            perm1 = perm1_fn(total.shape[0], local_slot)

            def one_class(acc, inputs):
                req, k = inputs
                counts = class_solve(req, k, total, acc, alive, perm1,
                                     threshold, cap_max)
                return acc - counts[:, None] * req[None, :], counts

            _, counts = jax.lax.scan(one_class, avail, (reqs, ks))
            usage = jnp.einsum("cn,cr->nr", counts, reqs)
            return avail - usage, usage, counts.astype(jnp.int32)

        return jax.jit(step, donate_argnums=(0,))

    def pipelined_step(self, avail_dev, freed_dev, delta_dev, reqs, ks,
                       total_dev, alive_dev, local_slot: int,
                       opts: SchedulingOptions):
        """Dispatch one double-buffered drain step asynchronously.
        Returns (avail', usage, counts) device arrays WITHOUT blocking —
        the caller overlaps host commit of the previous tick with this
        solve and only syncs on the counts pull. ``avail_dev`` is
        donated (consumed); use the returned availability."""
        if self._jax_pipelined is None:
            self._jax_pipelined = self._build_jax_pipelined_step()
        reqs, ks = self._to_f32(reqs, ks)
        return self._jax_pipelined(avail_dev, freed_dev, delta_dev, reqs,
                                   ks, total_dev, alive_dev, local_slot,
                                   opts.spread_threshold)

    @staticmethod
    def _to_f32(*arrays):
        """Host-side float32 coercion BEFORE device transfer: int64
        fixed-point above 2^31 would wrap negative if jax truncated it to
        int32 (x64 off), making feasible nodes look infeasible. float32
        keeps the magnitude (approximately); capacity off-by-ones from
        rounding are repaired by the caller's exact-host fallback."""
        import jax.numpy as jnp

        out = []
        for a in arrays:
            if isinstance(a, np.ndarray) and a.dtype != np.float32:
                out.append(np.asarray(a, dtype=np.float32))
            elif hasattr(a, "dtype") and a.dtype not in (np.float32, bool):
                out.append(jnp.asarray(a, dtype=jnp.float32))
            else:
                out.append(a)
        return out

    def schedule_tick_fused(self, reqs, ks, total, available, alive,
                            local_slot: int, opts: SchedulingOptions):
        """One-dispatch whole-queue schedule; returns a device array
        [C, N]. Callers must pass the pulled counts through
        ``repair_oversubscription`` before committing them — the device
        solve runs in float32, and magnitudes above 2^24 can round a
        capacity up by one."""
        if self._jax_fused is None:
            self._jax_fused = self._build_jax_fused()
        reqs, ks, total, available = self._to_f32(reqs, ks, total, available)
        return self._jax_fused(reqs, ks, total, available, alive,
                               local_slot, opts.spread_threshold)

    @staticmethod
    def repair_oversubscription(reqs: np.ndarray, counts: np.ndarray,
                                available: np.ndarray) -> np.ndarray:
        """Exact int64 host pass over fused-tick output: clamp each
        class's per-node count to the capacity actually left after the
        preceding classes committed.

        Fast path: if the WHOLE batch fits (``available - total_usage >=
        0`` everywhere), no class can be over capacity after its
        predecessors either — usage is non-negative, so every prefix sum
        is bounded by the total — and the per-class clamp loop is
        skipped. The loop only runs on an actual f32 capacity
        off-by-one, which needs fixed-point magnitudes near 2^24."""
        counts = np.asarray(counts, dtype=np.int64)
        reqs = np.asarray(reqs, dtype=np.int64)
        avail = np.asarray(available, dtype=np.int64)
        usage = counts.T @ reqs                 # [N, R] int64, exact
        if np.all(avail >= usage):
            return counts.copy()
        counts = counts.copy()
        avail = avail.copy()
        for c in range(counts.shape[0]):
            req = reqs[c]                      # [R]
            pos = req > 0
            if pos.any():
                # [N]: exact max placements per node for this class
                cap = np.min(avail[:, pos] // req[pos], axis=1)
                cap = np.maximum(cap, 0)
                counts[c] = np.minimum(counts[c], cap)
            avail -= counts[c][:, None] * req[None, :]
        return counts

    def schedule_classes(
        self,
        reqs: np.ndarray,          # [C, R]
        ks: np.ndarray,            # [C]
        total: np.ndarray,
        available: np.ndarray,
        alive: np.ndarray,
        local_slot: int,
        opts: SchedulingOptions,
    ) -> np.ndarray:
        """Schedule C scheduling classes at once -> [C, N] counts.

        Classes are committed in order; a later class sees availability
        reduced by earlier classes' placements (host-side fixup loop kept
        cheap because C is small in practice).
        """
        if self.use_jax:
            if self._jax_fn is None:
                self._jax_fn = self._build_jax()
            out = np.zeros((reqs.shape[0], total.shape[0]), dtype=np.int64)
            avail = available.copy()
            # One device solve per class against committed availability —
            # exact parity with the sequential path. The node axis (the
            # large one: 100k-task queues collapse into few classes over
            # many nodes) stays fully vectorized on device.
            total_f, = self._to_f32(total)
            for c in range(reqs.shape[0]):
                req_f, k_f, avail_f = self._to_f32(
                    reqs[c], np.float32(ks[c]), avail)
                counts = np.asarray(
                    self._jax_fn(req_f, k_f, total_f, avail_f, alive,
                                 local_slot, opts.spread_threshold)
                ).astype(np.int64)
                used = counts[:, None] * reqs[c][None, :]
                if np.any((avail - used) < 0):
                    # float32 capacity off-by-one on huge magnitudes:
                    # repair with the exact host solve
                    counts = self.schedule_class(
                        reqs[c], int(ks[c]), total, avail, alive,
                        local_slot, opts)
                    used = counts[:, None] * reqs[c][None, :]
                avail = avail - used
                out[c] = counts
            return out
        out = np.zeros((reqs.shape[0], total.shape[0]), dtype=np.int64)
        avail = available.copy()
        for c in range(reqs.shape[0]):
            counts = self.schedule_class(
                reqs[c], int(ks[c]), total, avail, alive, local_slot, opts)
            avail = avail - counts[:, None] * reqs[c][None, :]
            out[c] = counts
        return out


class DeviceMatrixMirror:
    """Device-resident ``total/available/alive`` mirror of a host
    :class:`~ray_tpu.scheduler.resources.ResourceMatrix`.

    The pipelined scheduler tick solves against these buffers instead of
    re-coercing and re-uploading the full ``[nodes x resources]`` matrix
    every tick (ROADMAP Open item 2: the upload was pure host time
    between device solves). Freshness protocol:

      - a ``matrix.version`` jump (new node, wider resource axis,
        liveness flip) forces a FULL re-sync;
      - otherwise only the rows ``matrix.consume_dirty_rows()`` reports
        (commit/heartbeat deltas) are folded in by one small jitted
        scatter with a DONATED destination buffer — an in-place device
        update, bytes proportional to changed rows;
      - every ``sync_period`` delta refreshes a full re-sync runs anyway
        so numerical drift (f32 folding of >2^24 fixed-point rows)
        cannot accumulate;
      - ``debug_check`` compares the folded device availability against
        the host matrix elementwise after every refresh and raises on
        the first divergence (the drift guard for development and the
        scheduler_pipeline test marker).

    Synchronization: callers hold the cluster lock while calling
    ``refresh`` (it reads the host matrix), and must NOT hold it while
    blocking on device results. The returned arrays are functionally
    immutable; using them after the lock is released is safe.
    """

    def __init__(self):
        self._version: Optional[int] = None
        self._total = None
        self._avail = None
        self._alive = None
        self._refreshes_since_full = 0
        self._set_rows_fn = None
        # observability: bench.py reports upload bytes per tick off/on
        self.upload_bytes_total = 0
        self.full_syncs = 0
        self.delta_syncs = 0

    @staticmethod
    def _build_set_rows():
        import jax

        def set_rows(total, avail, idx, rows_t, rows_a):
            return total.at[idx].set(rows_t), avail.at[idx].set(rows_a)

        return jax.jit(set_rows, donate_argnums=(0, 1))

    def refresh(self, matrix, sync_period: int,
                debug_check: bool = False) -> Tuple:
        """Bring the mirror up to date with the host matrix; returns
        ``(total, available, alive, uploaded_bytes)`` device arrays in
        the solve's f32/bool layout. Caller holds the cluster lock."""
        import jax

        full = (self._total is None
                or self._version != matrix.version
                or self._refreshes_since_full >= max(1, int(sync_period)))
        if full:
            self._total = jax.device_put(
                np.asarray(matrix.total, dtype=np.float32))
            self._avail = jax.device_put(
                np.asarray(matrix.available, dtype=np.float32))
            self._alive = jax.device_put(np.asarray(matrix.alive))
            matrix.consume_dirty_rows()  # subsumed by the full upload
            self._version = matrix.version
            self._refreshes_since_full = 0
            self.full_syncs += 1
            uploaded = (self._total.nbytes + self._avail.nbytes
                        + self._alive.nbytes)
        else:
            self._refreshes_since_full += 1
            idx = matrix.consume_dirty_rows()
            uploaded = 0
            if idx.size:
                # pad the row set to a power-of-two bucket (repeating the
                # last row — scatter-set is idempotent for identical
                # rows) so the jitted scatter compiles per bucket, not
                # per distinct dirty-count
                bucket = 1 << int(idx.size - 1).bit_length()
                if bucket > idx.size:
                    idx = np.concatenate(
                        [idx, np.repeat(idx[-1:], bucket - idx.size)])
                idx = idx.astype(np.int32)
                rows_t = np.asarray(matrix.total[idx], dtype=np.float32)
                rows_a = np.asarray(matrix.available[idx],
                                    dtype=np.float32)
                if self._set_rows_fn is None:
                    self._set_rows_fn = self._build_set_rows()
                self._total, self._avail = self._set_rows_fn(
                    self._total, self._avail, idx, rows_t, rows_a)
                self.delta_syncs += 1
                uploaded = rows_t.nbytes + rows_a.nbytes + idx.nbytes
        self.upload_bytes_total += uploaded
        if debug_check:
            host_a = np.asarray(matrix.available, dtype=np.float32)
            dev_a = np.asarray(self._avail)
            if not np.array_equal(host_a, dev_a):
                bad = int((host_a != dev_a).sum())
                raise AssertionError(
                    f"device matrix mirror drifted from host on {bad} "
                    f"cell(s) (version={matrix.version}, "
                    f"since_full={self._refreshes_since_full})")
        return self._total, self._avail, self._alive, uploaded


_shared_policies: Dict[bool, BatchedHybridPolicy] = {}


def shared_batched_policy(use_jax: bool) -> BatchedHybridPolicy:
    """Process-wide shared instance per backend flavor. The jit caches
    live on the instance; in-process clusters run hundreds of raylets in
    one interpreter, and per-raylet instances would recompile the same
    fused tick kernel hundreds of times."""
    policy = _shared_policies.get(use_jax)
    if policy is None:
        policy = _shared_policies.setdefault(
            use_jax, BatchedHybridPolicy(use_jax=use_jax))
    return policy


_device_ok: Optional[bool] = None
_device_ok_ts: float = 0.0
_device_probe_running = False
_device_probe_lock = threading.Lock()
# A verdict this old no longer covers the backend: the tick returns to
# numpy and a fresh background probe runs (same freshness discipline as
# the driver's probe cache: in-process jax only on a recent "ok").
_DEVICE_OK_TTL_S = 300.0

# NOTE: this is deliberately NOT the driver-side probe in
# __graft_entry__ (same subprocess snippet, different cache): the
# library cannot depend on a repo-root driver artifact, the runtime
# gate needs per-process TTL re-probing for a long-lived raylet, and
# it never blocks the caller (background thread) where the driver's
# probe is synchronous.


def device_solve_available() -> bool:
    """Gate for routing LIVE scheduling ticks through the jit solve.

    The host CPU backend resolves immediately. Any other default
    backend (a locally-attached chip, or the wedge-prone tunneled-TPU
    plugin) is probed in a background-thread subprocess, and the "ok"
    verdict expires after _DEVICE_OK_TTL_S (a backend that wedges
    after one good probe must not hang a later tick in native code —
    the tick path has no subprocess watchdog of its own). Until a
    fresh probe lands, the caller stays on numpy. (Reference posture:
    the TPU policy is an opt-in sibling behind the SchedulingPolicy
    seam, never a liveness hazard for the raylet.)"""
    global _device_probe_running
    import os
    import time

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        return True  # host CPU cannot wedge
    fresh = (_device_ok is not None
             and time.monotonic() - _device_ok_ts < _DEVICE_OK_TTL_S)
    if fresh:
        return bool(_device_ok)
    with _device_probe_lock:
        if not _device_probe_running:
            _device_probe_running = True
            threading.Thread(target=_device_probe_bg, daemon=True,
                             name="device-solve-probe").start()
    # expired or never probed: numpy until the background probe lands
    return False


def _probe_backend_key() -> str:
    """The cache key for a probe verdict: the backend the probe would
    exercise. JAX_PLATFORMS is what routes the subprocess's jit."""
    import os

    return os.environ.get("JAX_PLATFORMS", "").strip() or "default"


def _probe_cache_path() -> str:
    import hashlib
    import os
    import tempfile

    digest = hashlib.sha1(
        _probe_backend_key().encode()).hexdigest()[:12]
    uid = f"-{os.getuid()}" if hasattr(os, "getuid") else ""
    return os.path.join(tempfile.gettempdir(),
                        f"ray_tpu_device_probe{uid}-{digest}.json")


def _probe_cache_load():
    """A fresh same-backend verdict from a previous process on this
    host, or None. Freshness is file mtime age under the same TTL the
    in-process cache uses (fs wall-clock discipline, like the
    byte_store sweep)."""
    import json
    import os
    import time

    path = _probe_cache_path()
    try:
        # raycheck: disable=RC02 — fs-mtime freshness vs wall clock, not deadline arithmetic
        age = time.time() - os.path.getmtime(path)
        if not (0 <= age < _DEVICE_OK_TTL_S):
            return None
        with open(path, "r", encoding="utf-8") as f:
            cached = json.load(f)
        if (cached.get("backend") == _probe_backend_key()
                and isinstance(cached.get("ok"), bool)):
            return cached["ok"]
    except Exception as e:  # noqa: BLE001 — unreadable cache = no cache
        logger = __import__("logging").getLogger(__name__)
        logger.debug("device probe cache read failed: %r", e)
    return None


def _probe_cache_store(ok: bool) -> None:
    import json
    import os

    path = _probe_cache_path()
    try:
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"ok": ok, "backend": _probe_backend_key()}, f)
        os.replace(tmp, path)  # atomic: concurrent probes race cleanly
    except Exception as e:  # noqa: BLE001 — cache is best-effort
        logger = __import__("logging").getLogger(__name__)
        logger.debug("device probe cache write failed: %r", e)


def _device_probe_bg() -> None:
    global _device_ok, _device_ok_ts, _device_probe_running
    import os
    import subprocess
    import sys
    import time

    force = os.environ.get("RAY_TPU_FORCE_DEVICE_PROBE", "").lower() in (
        "1", "true", "yes")
    try:
        if not force:
            cached = _probe_cache_load()
            if cached is not None:
                # another process on this host probed this backend
                # recently — skip the ~seconds-long subprocess boot
                _device_ok = cached
                return
        code = ("import jax, jax.numpy as jnp; "
                "jax.jit(lambda x: x.sum())(jnp.ones((8, 8)))"
                ".block_until_ready()")
        try:
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, timeout=60)
            _device_ok = proc.returncode == 0
        except Exception:  # noqa: BLE001 — any failure means "stay on numpy"
            _device_ok = False
        _probe_cache_store(bool(_device_ok))
    finally:
        _device_ok_ts = time.monotonic()
        with _device_probe_lock:
            _device_probe_running = False
