"""Pull manager — priority admission over object pull bundles.

North-star component #3 (SURVEY §2.1, reference:
src/ray/object_manager/pull_manager.{h,cc}): pulls are requested as
*bundles* (all args of one task, or one get/wait call) with a strict
priority order — GET_REQUEST > WAIT_REQUEST > TASK_ARGS
(pull_manager.h:37-45) — and admission control activates only the
prefix of bundles whose total size fits the available store budget
(UpdatePullsBasedOnAvailableMemory), always at least one so progress
is never wedged.

The reference walks its queues bundle-by-bundle per update. Here the
admission solve is one vectorized pass over the whole queue: order by
(priority, sequence), prefix-sum the sizes, and threshold against the
budget — numpy for the typical queue, the same arithmetic jnp-jittable
for the 100k-bundle regime (bench.py measures the scheduler twin of
this kernel).

In this build objects restore from spill files rather than remote
nodes, so "activating" a bundle grants restore admission; the same
seam carries node-to-node transfer when raylets are remote.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class BundlePriority(IntEnum):
    """Lower value = higher priority (pull_manager.h:37-45)."""

    GET_REQUEST = 0
    WAIT_REQUEST = 1
    TASK_ARGS = 2


@dataclass
class PullBundle:
    bundle_id: int
    priority: BundlePriority
    object_ids: Tuple
    total_size: int
    seq: int
    active: bool = False
    # signalled when the bundle becomes active
    event: threading.Event = field(default_factory=threading.Event)


class PullManager:
    def __init__(self, capacity_bytes: int,
                 admission_fraction: Optional[float] = None,
                 on_activate: Optional[Callable[[PullBundle], None]] = None,
                 on_deactivate: Optional[Callable[[PullBundle], None]] = None):
        from ray_tpu._private.config import Config

        self.capacity = int(capacity_bytes)
        self.admission_fraction = (
            admission_fraction if admission_fraction is not None
            else Config.instance().pull_manager_admission_fraction)
        self.on_activate = on_activate
        self.on_deactivate = on_deactivate
        self._lock = threading.Lock()
        self._bundles: Dict[int, PullBundle] = {}
        self._next_id = 1
        self._next_seq = 0
        self.num_admission_ticks = 0

    # ----------------------------------------------------------------- API
    def pull(self, priority: BundlePriority, object_ids: Sequence,
             sizes: Sequence[int]) -> int:
        """Queue one bundle (reference: PullManager::Pull,
        pull_manager.h:86). Returns the bundle id for cancel()."""
        with self._lock:
            bundle = PullBundle(
                bundle_id=self._next_id,
                priority=BundlePriority(priority),
                object_ids=tuple(object_ids),
                total_size=int(sum(sizes)),
                seq=self._next_seq,
            )
            self._next_id += 1
            self._next_seq += 1
            self._bundles[bundle.bundle_id] = bundle
        self.admission_tick()
        return bundle.bundle_id

    def cancel(self, bundle_id: int) -> None:
        """CancelPull: frees the bundle's budget; the next tick may
        activate queued bundles."""
        with self._lock:
            self._bundles.pop(bundle_id, None)
        self.admission_tick()

    def update_capacity(self, capacity_bytes: int) -> None:
        self.capacity = int(capacity_bytes)
        self.admission_tick()

    def is_active(self, bundle_id: int) -> bool:
        with self._lock:
            bundle = self._bundles.get(bundle_id)
            return bool(bundle and bundle.active)

    def wait_active(self, bundle_id: int, timeout: Optional[float] = None
                    ) -> bool:
        with self._lock:
            bundle = self._bundles.get(bundle_id)
        if bundle is None:
            return False
        return bundle.event.wait(timeout)

    # ------------------------------------------------------- admission tick
    def admission_tick(self) -> None:
        """One vectorized admission solve
        (UpdatePullsBasedOnAvailableMemory): activate the
        (priority, seq)-ordered prefix fitting the budget; always admit
        the head bundle even when oversized so gets can't wedge."""
        newly_active: List[PullBundle] = []
        newly_inactive: List[PullBundle] = []
        with self._lock:
            self.num_admission_ticks += 1
            if not self._bundles:
                return
            bundles = list(self._bundles.values())
            prio = np.fromiter((b.priority for b in bundles), np.int64)
            seq = np.fromiter((b.seq for b in bundles), np.int64)
            sizes = np.fromiter((b.total_size for b in bundles), np.int64)
            order = np.lexsort((seq, prio))
            budget = int(self.capacity * self.admission_fraction)
            csum = np.cumsum(sizes[order])
            admit_sorted = csum <= budget
            admit_sorted[0] = True  # head always progresses
            admitted = np.zeros(len(bundles), dtype=bool)
            admitted[order] = admit_sorted
            for b, adm in zip(bundles, admitted):
                if adm and not b.active:
                    b.active = True
                    b.event.set()
                    newly_active.append(b)
                elif not adm and b.active:
                    b.active = False
                    b.event.clear()
                    newly_inactive.append(b)
        for b in newly_active:
            if self.on_activate:
                self.on_activate(b)
        for b in newly_inactive:
            if self.on_deactivate:
                self.on_deactivate(b)

    # ---------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            active = sum(1 for b in self._bundles.values() if b.active)
            return {
                "num_bundles": len(self._bundles),
                "num_active": active,
                "num_queued": len(self._bundles) - active,
                "active_bytes": sum(b.total_size
                                    for b in self._bundles.values()
                                    if b.active),
                "capacity": self.capacity,
                "admission_ticks": self.num_admission_ticks,
            }
