"""Placement groups: gang-scheduling bundles of resources.

Re-implements the reference's placement-group plane:
  - the GCS-side packer (gcs/gcs_server/gcs_placement_group_scheduler.cc
    GcsScheduleStrategy subclasses; gcs_resource_scheduler.cc
    LeastResourceScorer) as a *vectorized* solve: bundle x node demand
    matrices scored in one shot, then a strategy-specific masked greedy
    assignment;
  - the raylet-side 2-phase commit of bundle resources
    (raylet/placement_group_resource_manager.h:51 Prepare/Commit/Return)
    including the shadow resources tasks schedule against
    (``<R>_group_<index>_<pgid>`` / ``<R>_group_<pgid>``);
  - the user API surface (python/ray/util/placement_group.py).

The strategies (common.proto PlacementStrategy):
  PACK          bundles together, as few nodes as possible (soft)
  SPREAD        bundles apart, best-effort
  STRICT_PACK   all bundles on one node, or fail
  STRICT_SPREAD every bundle on a distinct node, or fail
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu._private.ids import NodeID, PlacementGroupID
from ray_tpu.exceptions import PlacementGroupError
from ray_tpu.scheduler.resources import ResourceRequest, to_fixed

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroupState(Enum):
    PENDING = 0
    CREATED = 1
    REMOVED = 2
    RESCHEDULING = 3


def _pg_hex(pg_id) -> str:
    # accepts a PlacementGroupID or an already-hex string (process tier)
    return pg_id if isinstance(pg_id, str) else pg_id.hex()


def bundle_resource_name(resource: str, pg_id: PlacementGroupID,
                         bundle_index: Optional[int] = None) -> str:
    """Shadow-resource naming, matching the reference's
    FormatPlacementGroupResource (bundle_spec.cc)."""
    if bundle_index is None:
        return f"{resource}_group_{_pg_hex(pg_id)}"
    return f"{resource}_group_{bundle_index}_{_pg_hex(pg_id)}"


def shadow_resources_for_bundle(bundle: Dict[str, float],
                                pg_id: PlacementGroupID,
                                bundle_index: int) -> Dict[str, float]:
    """Capacities a node gains when a bundle commits: per-index names plus
    the wildcard names that sum across bundles on that node."""
    out: Dict[str, float] = {}
    for resource, amount in bundle.items():
        out[bundle_resource_name(resource, pg_id, bundle_index)] = amount
        wildcard = bundle_resource_name(resource, pg_id)
        out[wildcard] = out.get(wildcard, 0) + amount
    # marker resource so zero-demand tasks can still pin to the bundle
    out[bundle_resource_name("bundle", pg_id, bundle_index)] = 1000
    out[bundle_resource_name("bundle", pg_id)] = (
        out.get(bundle_resource_name("bundle", pg_id), 0) + 1000)
    return out


def rewrite_resources_for_pg(resources: Dict[str, float], pg,
                             bundle_index: int) -> Dict[str, float]:
    """Rewrite a task's demand onto a PG's shadow resources
    (reference: placement group resource mapping in task submission,
    actor.py/remote_function.py _configure_placement_group)."""
    pg_id = pg.id
    out: Dict[str, float] = {}
    idx = bundle_index if bundle_index >= 0 else None
    for resource, amount in resources.items():
        out[bundle_resource_name(resource, pg_id, idx)] = amount
    # always consume a sliver of the bundle marker so placement works even
    # for zero-resource tasks
    out[bundle_resource_name("bundle", pg_id, idx)] = 0.001
    return out


@dataclass
class PlacementGroup:
    """User-facing handle (reference: util/placement_group.py)."""

    id: PlacementGroupID
    bundles: List[Dict[str, float]]
    strategy: str = "PACK"
    name: str = ""
    state: PlacementGroupState = PlacementGroupState.PENDING
    # committed node per bundle, parallel to `bundles`
    bundle_nodes: List[Optional[NodeID]] = field(default_factory=list)
    capture_child_tasks: bool = False
    lifetime: Optional[str] = None
    _ready_event: threading.Event = field(default_factory=threading.Event)

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return self.bundles

    def ready(self):
        """Returns an ObjectRef resolved when the PG is placed
        (reference: util/placement_group.py PlacementGroup.ready)."""
        return _ready_ref(self)

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self._ready_event.wait(timeout=timeout_seconds)

    def is_ready(self) -> bool:
        return self.state is PlacementGroupState.CREATED


def _ready_ref(pg: PlacementGroup):
    from ray_tpu._private.ids import ObjectID, TaskID
    from ray_tpu.core import runtime as rt_mod
    from ray_tpu.core.object_ref import ObjectRef

    rt = rt_mod.global_runtime
    ctx = rt.context()
    ctx.put_counter += 1
    oid = ObjectID.for_put(ctx.task_id, ctx.put_counter)
    rt.reference_counter.add_owned_object(oid)

    def _resolver():
        pg._ready_event.wait()
        rt.object_store.put(oid, pg)

    threading.Thread(target=_resolver, daemon=True).start()
    return ObjectRef(oid)


class LeastResourceScorer:
    """Best-fit scoring, vectorized over nodes
    (reference: gcs_resource_scheduler.h:54 LeastResourceScorer — higher
    score == better; prefers nodes left with the least slack)."""

    @staticmethod
    def score(demand: np.ndarray, available: np.ndarray,
              total: np.ndarray) -> np.ndarray:
        # [N] float; -inf where infeasible
        feasible = np.all(available >= demand, axis=1)
        with np.errstate(divide="ignore", invalid="ignore"):
            slack = np.where(
                total > 0,
                (available - demand) / np.maximum(total, 1),
                0.0,
            ).sum(axis=1)
        score = -slack  # least remaining == best fit
        return np.where(feasible, score, -np.inf)


class PlacementGroupManager:
    """GCS-side PG lifecycle: pack -> 2PC -> track
    (reference: gcs_placement_group_manager.cc FSM + scheduler)."""

    def __init__(self, runtime):
        self._rt = runtime
        self._lock = threading.RLock()
        self._groups: Dict[PlacementGroupID, PlacementGroup] = {}
        self._pending: List[PlacementGroup] = []
        self._named: Dict[str, PlacementGroupID] = {}

    # ------------------------------------------------------------- create
    def create(self, pg: PlacementGroup) -> None:
        with self._lock:
            if pg.name:
                if pg.name in self._named:
                    raise ValueError(
                        f"placement group name {pg.name!r} already taken")
                self._named[pg.name] = pg.id
            self._groups[pg.id] = pg
        if not self._try_place(pg):
            with self._lock:
                self._pending.append(pg)

    def pending_pgs(self) -> List["PlacementGroup"]:
        """Unplaced groups — the autoscaler's PG demand feed (reference:
        the monitor forwards pending PG bundles to the demand scheduler)."""
        with self._lock:
            return list(self._pending)

    def retry_pending(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
        still = []
        for pg in pending:
            if pg.state is PlacementGroupState.REMOVED:
                continue
            if not self._try_place(pg):
                still.append(pg)
        if still:
            with self._lock:
                self._pending.extend(still)

    # -------------------------------------------------------------- solve
    def _try_place(self, pg: PlacementGroup) -> bool:
        rt = self._rt
        cluster = rt.cluster_state
        with cluster.lock:
            cluster.refresh_locked()
            matrix = cluster.matrix
            node_ids = matrix.node_ids()
            alive = matrix.alive.copy()
            available = matrix.available.copy()
            total = matrix.total.copy()
        width = matrix.width
        demands = np.zeros((len(pg.bundles), width), dtype=np.int64)
        for i, bundle in enumerate(pg.bundles):
            req = ResourceRequest.from_map(bundle, cluster.ids)
            if max(req.demands.keys(), default=-1) >= width:
                return False  # resource no node has yet
            demands[i] = req.dense(width)
        available = np.where(alive[:, None], available, -1)
        assignment = self._solve(pg.strategy, demands, available, total)
        if assignment is None:
            return False
        chosen = [node_ids[slot] for slot in assignment]
        return self._two_phase_commit(pg, chosen)

    def _solve(self, strategy: str, demands: np.ndarray,
               available: np.ndarray, total: np.ndarray
               ) -> Optional[List[int]]:
        """Vectorized packer. Returns node slot per bundle or None."""
        n_bundles, n_nodes = demands.shape[0], available.shape[0]
        if n_nodes == 0:
            return None
        avail = available.copy()
        if strategy == "STRICT_PACK":
            whole = demands.sum(axis=0)
            scores = LeastResourceScorer.score(whole, avail, total)
            best = int(np.argmax(scores))
            if not np.isfinite(scores[best]):
                return None
            return [best] * n_bundles
        assignment: List[int] = []
        used_nodes: set[int] = set()
        for i in range(n_bundles):
            scores = LeastResourceScorer.score(demands[i], avail, total)
            if strategy == "STRICT_SPREAD":
                for slot in used_nodes:
                    scores[slot] = -np.inf
            elif strategy == "SPREAD":
                # soft: penalize already-used nodes
                for slot in used_nodes:
                    if np.isfinite(scores[slot]):
                        scores[slot] -= 1000.0
            elif strategy == "PACK":
                # soft: prefer already-used nodes
                for slot in used_nodes:
                    if np.isfinite(scores[slot]):
                        scores[slot] += 1000.0
            best = int(np.argmax(scores))
            if not np.isfinite(scores[best]):
                return None
            assignment.append(best)
            used_nodes.add(best)
            avail[best] = avail[best] - demands[i]
        return assignment

    # ---------------------------------------------------------------- 2PC
    def _two_phase_commit(self, pg: PlacementGroup,
                          chosen: List[NodeID]) -> bool:
        """PrepareBundleResources on every raylet; all-or-nothing, then
        CommitBundleResources (reference: node_manager.h:475-485,
        placement_group_resource_manager.h:88). Both phases are
        idempotent raylet-side (core/raylet.py keys bundle state by
        (pg_id, bundle_index)), so a retried attempt after a partial
        failure cannot double-reserve or double-apply shadow capacity.
        A node vanishing between prepare and commit rolls the whole
        attempt back instead of leaking the other nodes' reservations."""
        rt = self._rt

        def rollback(entries: List[Tuple[int, NodeID]]) -> None:
            for pidx, pnode in entries:
                pr = rt.cluster_state.raylets.get(pnode)
                if pr is not None:
                    pr.return_bundle(pg.id, pidx, pg.bundles[pidx],
                                     committed=True)

        prepared: List[Tuple[int, NodeID]] = []
        for index, node_id in enumerate(chosen):
            raylet = rt.cluster_state.raylets.get(node_id)
            if raylet is None or not raylet.prepare_bundle(
                    pg.id, index, pg.bundles[index]):
                rollback(prepared)
                return False
            prepared.append((index, node_id))
        for index, node_id in enumerate(chosen):
            raylet = rt.cluster_state.raylets.get(node_id)
            if raylet is None:  # died between prepare and commit
                rollback(prepared)
                return False
            raylet.commit_bundle(pg.id, index, pg.bundles[index])
        pg.bundle_nodes = list(chosen)
        pg.state = PlacementGroupState.CREATED
        pg._ready_event.set()
        return True

    # -------------------------------------------------------------- remove
    def remove(self, pg_id: PlacementGroupID) -> None:
        with self._lock:
            pg = self._groups.get(pg_id)
            if pg is None or pg.state is PlacementGroupState.REMOVED:
                return
            if pg in self._pending:
                self._pending.remove(pg)
            if pg.name:
                self._named.pop(pg.name, None)
            was_created = pg.state is PlacementGroupState.CREATED
            pg.state = PlacementGroupState.REMOVED
        if was_created:
            for index, node_id in enumerate(pg.bundle_nodes):
                raylet = self._rt.cluster_state.raylets.get(node_id)
                if raylet is not None:
                    raylet.return_bundle(pg.id, index, pg.bundles[index],
                                         committed=True)

    def handle_node_death(self, node_id: NodeID) -> None:
        """Bundles on a dead node put the PG into RESCHEDULING
        (reference: gcs_placement_group_manager.cc OnNodeDead)."""
        to_retry = []
        with self._lock:
            for pg in self._groups.values():
                if pg.state is PlacementGroupState.CREATED and any(
                        n == node_id for n in pg.bundle_nodes):
                    pg.state = PlacementGroupState.RESCHEDULING
                    pg._ready_event.clear()
                    # return surviving bundles, then re-place the whole PG
                    for index, n in enumerate(pg.bundle_nodes):
                        if n != node_id:
                            raylet = self._rt.cluster_state.raylets.get(n)
                            if raylet is not None:
                                raylet.return_bundle(
                                    pg.id, index, pg.bundles[index],
                                    committed=True)
                    pg.bundle_nodes = []
                    to_retry.append(pg)
        for pg in to_retry:
            if not self._try_place(pg):
                with self._lock:
                    self._pending.append(pg)

    def get(self, pg_id: PlacementGroupID) -> Optional[PlacementGroup]:
        with self._lock:
            return self._groups.get(pg_id)

    def get_by_name(self, name: str) -> Optional[PlacementGroup]:
        with self._lock:
            pg_id = self._named.get(name)
            return self._groups.get(pg_id) if pg_id else None

    def table(self) -> Dict[str, dict]:
        with self._lock:
            return {
                pg.id.hex(): {
                    "name": pg.name,
                    "strategy": pg.strategy,
                    "state": pg.state.name,
                    "bundles": pg.bundles,
                    "bundle_nodes": [
                        n.hex() if n else None for n in pg.bundle_nodes],
                }
                for pg in self._groups.values()
            }
