"""Resource model: fixed-point quantities, interning, and matrix views.

Mirrors the reference's scheduling data model:
  - resources are int64 fixed-point at 1/10000 granularity
    (src/ray/raylet/scheduling/fixed_point.h:24)
  - resource names are interned to dense int ids
    (scheduling_ids.h:26 StringIdMap)
  - a ResourceRequest / NodeResources pair of flat vectors
    (cluster_resource_data.h:62,145)

The TPU-first twist: the whole cluster's resource state is *also* held as a
dense ``[num_nodes, num_resources]`` int64 matrix so the scheduling policy
can be evaluated as one batched device computation instead of a per-node
scan. ``ResourceMatrix`` is that view; it stays allocation-free across
ticks.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

import numpy as np

RESOURCE_UNIT_SCALING = 10000

# Predefined resources get fixed dense ids so matrices line up across
# nodes without consulting the interner (reference: scheduling_ids.h
# PredefinedResources enum).
CPU = "CPU"
MEMORY = "memory"
GPU = "GPU"
TPU = "TPU"
OBJECT_STORE_MEMORY = "object_store_memory"
PREDEFINED_RESOURCES = (CPU, MEMORY, GPU, TPU, OBJECT_STORE_MEMORY)


def to_fixed(value: float) -> int:
    """Convert a float resource quantity to int64 fixed point."""
    return int(round(value * RESOURCE_UNIT_SCALING))


def from_fixed(value: int) -> float:
    return value / RESOURCE_UNIT_SCALING


class StringIdMap:
    """Bidirectional string<->int interning, thread-safe.

    Predefined resources occupy ids [0, len(PREDEFINED_RESOURCES)); custom
    resources get the next free id. Ids are never reused.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._to_id: Dict[str, int] = {
            name: i for i, name in enumerate(PREDEFINED_RESOURCES)
        }
        self._to_str: List[str] = list(PREDEFINED_RESOURCES)

    def get_id(self, name: str) -> int:
        with self._lock:
            existing = self._to_id.get(name)
            if existing is not None:
                return existing
            new_id = len(self._to_str)
            self._to_id[name] = new_id
            self._to_str.append(name)
            return new_id

    def get_string(self, rid: int) -> str:
        with self._lock:
            return self._to_str[rid]

    def count(self) -> int:
        with self._lock:
            return len(self._to_str)


class ResourceRequest:
    """A task/bundle's resource demand as a sparse {resource_id: fixed}.

    (reference: cluster_resource_data.h:62 ResourceRequest)
    """

    __slots__ = ("demands",)

    def __init__(self, demands: Optional[Dict[int, int]] = None):
        self.demands: Dict[int, int] = {
            k: v for k, v in (demands or {}).items() if v != 0
        }

    @classmethod
    def from_map(cls, resources: Mapping[str, float], ids: StringIdMap
                 ) -> "ResourceRequest":
        return cls({ids.get_id(name): to_fixed(v)
                    for name, v in resources.items() if v != 0})

    def to_map(self, ids: StringIdMap) -> Dict[str, float]:
        return {ids.get_string(k): from_fixed(v) for k, v in self.demands.items()}

    def is_empty(self) -> bool:
        return not self.demands

    def dense(self, width: int) -> np.ndarray:
        out = np.zeros(width, dtype=np.int64)
        for k, v in self.demands.items():
            if k < width:
                out[k] = v
        return out

    def key(self) -> Tuple[Tuple[int, int], ...]:
        """Canonical hashable form — the SchedulingClass dedup key
        (reference: task_spec.h SchedulingClassDescriptor)."""
        return tuple(sorted(self.demands.items()))

    def __eq__(self, other):
        return isinstance(other, ResourceRequest) and self.demands == other.demands

    def __hash__(self):
        return hash(self.key())

    def __repr__(self):
        return f"ResourceRequest({self.demands})"


class NodeResources:
    """Total and available capacity of one node, sparse form.

    (reference: cluster_resource_data.h:145 NodeResources)
    """

    __slots__ = ("total", "available", "labels")

    def __init__(self, total: Optional[Dict[int, int]] = None,
                 available: Optional[Dict[int, int]] = None,
                 labels: Optional[Dict[str, str]] = None):
        self.total: Dict[int, int] = dict(total or {})
        self.available: Dict[int, int] = (
            dict(available) if available is not None else dict(self.total)
        )
        self.labels: Dict[str, str] = labels or {}

    @classmethod
    def from_map(cls, resources: Mapping[str, float], ids: StringIdMap
                 ) -> "NodeResources":
        total = {ids.get_id(name): to_fixed(v) for name, v in resources.items()}
        return cls(total=total)

    def is_feasible(self, req: ResourceRequest) -> bool:
        return all(self.total.get(rid, 0) >= amt for rid, amt in req.demands.items())

    def is_available(self, req: ResourceRequest) -> bool:
        return all(
            self.available.get(rid, 0) >= amt for rid, amt in req.demands.items()
        )

    def allocate(self, req: ResourceRequest) -> bool:
        if not self.is_available(req):
            return False
        for rid, amt in req.demands.items():
            self.available[rid] = self.available.get(rid, 0) - amt
        return True

    def free(self, req: ResourceRequest) -> None:
        for rid, amt in req.demands.items():
            if rid not in self.total:
                # capacity was removed while allocated (e.g. a placement
                # group bundle returned) — nothing to credit back
                continue
            self.available[rid] = min(
                self.available.get(rid, 0) + amt, self.total[rid]
            )

    def add_capacity(self, rid: int, amt: int) -> None:
        self.total[rid] = self.total.get(rid, 0) + amt
        self.available[rid] = self.available.get(rid, 0) + amt

    def remove_capacity(self, rid: int) -> None:
        self.total.pop(rid, None)
        self.available.pop(rid, None)

    def critical_utilization(self, width: Optional[int] = None) -> float:
        """max over resources of used/total — the hybrid policy's node score
        (reference: scheduling_policy.cc:41-57)."""
        score = 0.0
        for rid, tot in self.total.items():
            if tot <= 0:
                continue
            used = tot - self.available.get(rid, 0)
            score = max(score, used / tot)
        return score

    def to_map(self, ids: StringIdMap, available: bool = False) -> Dict[str, float]:
        src = self.available if available else self.total
        return {ids.get_string(k): from_fixed(v) for k, v in src.items()}

    def copy(self) -> "NodeResources":
        return NodeResources(dict(self.total), dict(self.available),
                             dict(self.labels))

    def __repr__(self):
        return f"NodeResources(total={self.total}, available={self.available})"


class ResourceMatrix:
    """Dense [nodes x resources] view of cluster state for the batched policy.

    Rebuilt incrementally: node rows are stable slots; resource columns grow
    as custom resources appear. All int64 fixed-point.
    """

    def __init__(self, ids: StringIdMap):
        self._ids = ids
        self._node_slots: Dict[object, int] = {}
        self._slot_nodes: List[object] = []
        self.total = np.zeros((0, len(PREDEFINED_RESOURCES)), dtype=np.int64)
        self.available = np.zeros((0, len(PREDEFINED_RESOURCES)), dtype=np.int64)
        self.alive = np.zeros((0,), dtype=bool)
        # Delta plumbing for device-resident mirrors (policy.py
        # DeviceMatrixMirror): `version` bumps on any STRUCTURAL change
        # (new node row, wider resource axis, liveness flip) — a mirror
        # seeing a version jump must full-resync; row-level capacity /
        # availability updates land in `_dirty_rows` and can be folded
        # into a mirror as a small per-row delta upload instead of
        # re-coercing and re-uploading the whole matrix every tick.
        # Synchronization contract: like the arrays themselves, these are
        # guarded by the caller's cluster lock.
        self.version = 0
        self._dirty_rows: set = set()

    @property
    def num_nodes(self) -> int:
        return len(self._slot_nodes)

    @property
    def width(self) -> int:
        return self.total.shape[1]

    def node_ids(self) -> List[object]:
        return list(self._slot_nodes)

    def slot_of(self, node_id) -> Optional[int]:
        return self._node_slots.get(node_id)

    def node_at(self, slot: int):
        return self._slot_nodes[slot]

    def _ensure_width(self, width: int) -> None:
        if width > self.total.shape[1]:
            pad = width - self.total.shape[1]
            self.total = np.pad(self.total, ((0, 0), (0, pad)))
            self.available = np.pad(self.available, ((0, 0), (0, pad)))
            self.version += 1

    def upsert(self, node_id, res: NodeResources) -> int:
        width = max(self._ids.count(),
                    max(res.total.keys(), default=-1) + 1,
                    self.total.shape[1])
        self._ensure_width(width)
        slot = self._node_slots.get(node_id)
        if slot is None:
            slot = len(self._slot_nodes)
            self._node_slots[node_id] = slot
            self._slot_nodes.append(node_id)
            self.total = np.vstack(
                [self.total, np.zeros((1, self.total.shape[1]), np.int64)])
            self.available = np.vstack(
                [self.available, np.zeros((1, self.total.shape[1]), np.int64)])
            self.alive = np.append(self.alive, True)
            self.version += 1
        row_t = np.zeros(self.total.shape[1], np.int64)
        row_a = np.zeros(self.total.shape[1], np.int64)
        for rid, amt in res.total.items():
            row_t[rid] = amt
        for rid, amt in res.available.items():
            row_a[rid] = amt
        self.total[slot] = row_t
        self.available[slot] = row_a
        self._dirty_rows.add(slot)
        return slot

    def set_alive(self, node_id, alive: bool) -> None:
        slot = self._node_slots.get(node_id)
        if slot is not None:
            self.alive[slot] = alive
            self.version += 1

    def consume_dirty_rows(self) -> np.ndarray:
        """Slots whose rows changed since the last call, cleared on read.
        A device mirror folds exactly these rows (commit/heartbeat
        deltas); an empty result means its buffers are already fresh."""
        if not self._dirty_rows:
            return np.zeros(0, dtype=np.int64)
        out = np.array(sorted(self._dirty_rows), dtype=np.int64)
        self._dirty_rows.clear()
        return out

    def requests_dense(self, requests: Iterable[ResourceRequest]) -> np.ndarray:
        reqs = list(requests)
        out = np.zeros((len(reqs), self.width), dtype=np.int64)
        for i, r in enumerate(reqs):
            for rid, amt in r.demands.items():
                if rid < self.width:
                    out[i, rid] = amt
                else:
                    # a resource no node has — mark infeasible via sentinel
                    self._ensure_width(rid + 1)
                    out = np.pad(out, ((0, 0), (0, self.width - out.shape[1])))
                    out[i, rid] = amt
        return out
