"""Distributed FIFO queue backed by an async actor.

Mirrors the reference's ray.util.queue.Queue (python/ray/util/queue.py):
put/get with block/timeout, *_nowait, batch variants, qsize/empty/full.
The backing actor is asyncio-based so blocked gets don't pin executor
threads.
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int = 0):
        self.queue = asyncio.Queue(maxsize)

    async def qsize(self):
        return self.queue.qsize()

    async def empty(self):
        return self.queue.empty()

    async def full(self):
        return self.queue.full()

    async def put(self, item, timeout: Optional[float] = None):
        try:
            await asyncio.wait_for(self.queue.put(item), timeout)
        except asyncio.TimeoutError:
            raise Full  # noqa: B904

    async def put_nowait(self, item):
        self.queue.put_nowait(item)

    async def put_nowait_batch(self, items):
        if self.queue.maxsize and (
                self.queue.qsize() + len(items) > self.queue.maxsize):
            raise Full(f"Cannot add {len(items)} items to queue of size "
                       f"{self.queue.qsize()} and maxsize {self.queue.maxsize}.")
        for item in items:
            self.queue.put_nowait(item)

    async def get(self, timeout: Optional[float] = None):
        try:
            return await asyncio.wait_for(self.queue.get(), timeout)
        except asyncio.TimeoutError:
            raise Empty  # noqa: B904

    async def get_nowait(self):
        try:
            return self.queue.get_nowait()
        except asyncio.QueueEmpty:
            raise Empty  # noqa: B904

    async def get_nowait_batch(self, num_items):
        if num_items > self.queue.qsize():
            raise Empty(f"Cannot get {num_items} items from queue of size "
                        f"{self.queue.qsize()}.")
        return [self.queue.get_nowait() for _ in range(num_items)]

    async def shutdown(self):
        return None


class Queue:
    def __init__(self, maxsize: int = 0,
                 actor_options: Optional[dict] = None):
        actor_options = actor_options or {}
        self.maxsize = maxsize
        self.actor = ray_tpu.remote(_QueueActor).options(
            **actor_options).remote(maxsize)

    def __len__(self) -> int:
        return self.size()

    def size(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def qsize(self) -> int:
        return self.size()

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            try:
                ray_tpu.get(self.actor.put_nowait.remote(item))
            except asyncio.QueueFull:
                raise Full  # noqa: B904
        else:
            if timeout is not None and timeout < 0:
                raise ValueError("'timeout' must be a non-negative number")
            ray_tpu.get(self.actor.put.remote(item, timeout))

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        ray_tpu.get(self.actor.put_nowait_batch.remote(items))

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            return ray_tpu.get(self.actor.get_nowait.remote())
        if timeout is not None and timeout < 0:
            raise ValueError("'timeout' must be a non-negative number")
        return ray_tpu.get(self.actor.get.remote(timeout))

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        return ray_tpu.get(self.actor.get_nowait_batch.remote(num_items))

    def shutdown(self, force: bool = False) -> None:
        if self.actor:
            if not force:
                ray_tpu.get(self.actor.shutdown.remote())
            ray_tpu.kill(self.actor)
        self.actor = None
