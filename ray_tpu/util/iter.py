"""ParallelIterator — lazy sharded iterators over actors.

Mirrors the reference's ray.util.iter (python/ray/util/iter.py):
from_items/from_range/from_iterators build a ParallelIterator of N shards
hosted on ParallelIteratorWorker actors; transformations (for_each,
filter, batch, flatten, ...) are lazy per-shard; gather_sync/gather_async
fold shards back into a LocalIterator on the driver.
"""

from __future__ import annotations

import collections
import random
from typing import Any, Callable, Iterable, Iterator, List, TypeVar

import ray_tpu

T = TypeVar("T")
U = TypeVar("U")


class _NextValueNotReady(Exception):
    pass


class ParallelIteratorWorker:
    """Actor hosting one shard's (possibly infinite) item sequence."""

    def __init__(self, item_generator: Any, repeat: bool):
        self.item_generator = item_generator
        self.repeat = repeat
        self.transforms: List[Callable[[Iterator], Iterator]] = []
        self.local_it: Iterator = None

    def _build_once(self) -> Iterator:
        if callable(self.item_generator):
            it = iter(self.item_generator())
        else:
            it = iter(self.item_generator)
        for t in self.transforms:
            it = t(it)
        return it

    def par_iter_init(self, transforms) -> None:
        self.transforms = transforms
        self.local_it = self._build_once()

    def par_iter_next(self):
        while True:
            try:
                return next(self.local_it)
            except StopIteration:
                if not self.repeat:
                    raise
                self.local_it = self._build_once()

    def par_iter_next_batch(self, batch_size: int):
        batch = []
        for _ in range(batch_size):
            try:
                batch.append(self.par_iter_next())
            except StopIteration:
                if batch:
                    return batch
                raise
        return batch

    def par_iter_slice(self, step: int, start: int):
        # used by union/select_shards-style access; kept for API parity
        out = []
        it = self._build_once()
        for i, item in enumerate(it):
            if i % step == start:
                out.append(item)
        return out


def from_items(items: List[T], num_shards: int = 2,
               repeat: bool = False) -> "ParallelIterator[T]":
    shards = [[] for _ in range(num_shards)]
    for i, item in enumerate(items):
        shards[i % num_shards].append(item)
    name = f"from_items[{items and type(items[0]).__name__ or 'None'}, " \
           f"{len(items)}, shards={num_shards}]"
    return from_iterators(shards, repeat=repeat, name=name)


def from_range(n: int, num_shards: int = 2,
               repeat: bool = False) -> "ParallelIterator[int]":
    generators = []
    shard = n // num_shards
    for i in range(num_shards):
        start = i * shard
        end = (i + 1) * shard if i < num_shards - 1 else n
        generators.append(range(start, end))
    return from_iterators(generators, repeat=repeat,
                          name=f"from_range[{n}, shards={num_shards}]")


def from_iterators(generators: List[Iterable[T]], repeat: bool = False,
                   name=None) -> "ParallelIterator[T]":
    worker_cls = ray_tpu.remote(ParallelIteratorWorker)
    actors = [worker_cls.remote(g, repeat) for g in generators]
    return from_actors(actors, name=name
                       or f"from_iterators[shards={len(generators)}]")


def from_actors(actors: List[Any], name=None) -> "ParallelIterator[T]":
    return ParallelIterator(actors, name or "from_actors", [])


class ParallelIterator:
    def __init__(self, actors: List[Any], name: str,
                 transforms: List[Callable]):
        self.actors = actors
        self.name = name
        self.transforms = transforms

    def __iter__(self):
        raise TypeError(
            "use gather_sync().__iter__() or gather_async().__iter__()")

    def __str__(self):
        return f"ParallelIterator[{self.name}]"

    __repr__ = __str__

    def _with_transform(self, fn: Callable[[Iterator], Iterator], suffix: str):
        return ParallelIterator(self.actors, self.name + suffix,
                                self.transforms + [fn])

    def for_each(self, fn: Callable[[T], U]) -> "ParallelIterator[U]":
        return self._with_transform(
            lambda it: map(fn, it), f".for_each({fn})")

    def filter(self, fn: Callable[[T], bool]) -> "ParallelIterator[T]":
        return self._with_transform(
            lambda it: filter(fn, it), f".filter({fn})")

    def batch(self, n: int) -> "ParallelIterator[List[T]]":
        def batcher(it):
            batch = []
            for item in it:
                batch.append(item)
                if len(batch) >= n:
                    yield batch
                    batch = []
            if batch:
                yield batch
        return self._with_transform(batcher, f".batch({n})")

    def flatten(self) -> "ParallelIterator":
        def flattener(it):
            for item in it:
                yield from item
        return self._with_transform(flattener, ".flatten()")

    def combine(self, fn: Callable[[T], List[U]]) -> "ParallelIterator[U]":
        return self.for_each(fn).flatten()

    def local_shuffle(self, shuffle_buffer_size: int,
                      seed: int = None) -> "ParallelIterator[T]":
        def shuffler(it):
            rng = random.Random(seed)
            buf = []
            for item in it:
                buf.append(item)
                if len(buf) >= shuffle_buffer_size:
                    yield buf.pop(rng.randrange(len(buf)))
            while buf:
                yield buf.pop(rng.randrange(len(buf)))
        return self._with_transform(
            shuffler,
            f".local_shuffle(buffer={shuffle_buffer_size}, seed={seed})")

    def repartition(self, num_partitions: int) -> "ParallelIterator[T]":
        # materialize and reshard (simplified vs reference's all-to-all slices)
        items = self.gather_sync().take(float("inf"))
        return from_items(items, num_shards=num_partitions)

    def num_shards(self) -> int:
        return len(self.actors)

    def shards(self) -> List["LocalIterator"]:
        return [self.select_shards([i]).gather_sync()
                for i in range(self.num_shards())]

    def select_shards(self, shards_to_keep: List[int]) -> "ParallelIterator[T]":
        return ParallelIterator(
            [a for i, a in enumerate(self.actors) if i in shards_to_keep],
            self.name + f".select_shards({shards_to_keep})", self.transforms)

    def gather_sync(self) -> "LocalIterator[T]":
        """Round-robin over shards, strictly in order."""
        for a in self.actors:
            ray_tpu.get(a.par_iter_init.remote(self.transforms))

        def base_iterator(timeout=None):
            actors = list(self.actors)
            while actors:
                for a in list(actors):
                    try:
                        yield ray_tpu.get(a.par_iter_next.remote())
                    except StopIteration:
                        actors.remove(a)
        return LocalIterator(base_iterator, name=self.name + ".gather_sync()")

    def gather_async(self, batch_ms: int = 0,
                     num_async: int = 1) -> "LocalIterator[T]":
        """Completion-order gather with num_async in-flight per shard."""
        for a in self.actors:
            ray_tpu.get(a.par_iter_init.remote(self.transforms))

        def base_iterator(timeout=None):
            in_flight = {}
            for a in self.actors:
                for _ in range(num_async):
                    in_flight[a.par_iter_next.remote()] = a
            while in_flight:
                ready, _ = ray_tpu.wait(
                    list(in_flight), num_returns=1, timeout=timeout)
                if not ready:
                    yield _NextValueNotReady()
                    continue
                [ref] = ready
                actor = in_flight.pop(ref)
                try:
                    value = ray_tpu.get(ref)
                except StopIteration:
                    continue
                except Exception:
                    raise
                in_flight[actor.par_iter_next.remote()] = actor
                yield value
        return LocalIterator(base_iterator, name=self.name + ".gather_async()")

    def take(self, n: int) -> List[T]:
        return self.gather_sync().take(n)

    def show(self, n: int = 20) -> None:
        self.gather_sync().show(n)

    def union(self, other: "ParallelIterator[T]") -> "ParallelIterator[T]":
        if self.transforms or other.transforms:
            # bake transforms into fresh local iterators via gather
            raise ValueError("union() requires untransformed iterators")
        return ParallelIterator(self.actors + other.actors,
                                f"union({self.name}, {other.name})", [])


class LocalIterator:
    """Driver-side iterator over gathered shard output."""

    def __init__(self, base_iterator: Callable[..., Iterator[T]],
                 local_transforms: List[Callable] = None, name: str = ""):
        self.base_iterator = base_iterator
        self.local_transforms = local_transforms or []
        self.name = name or "LocalIterator"

    def __iter__(self):
        it = self.base_iterator()
        for t in self.local_transforms:
            it = t(it)
        for item in it:
            if isinstance(item, _NextValueNotReady):
                continue
            yield item

    def __str__(self):
        return f"LocalIterator[{self.name}]"

    __repr__ = __str__

    def _with(self, fn, suffix):
        return LocalIterator(self.base_iterator,
                             self.local_transforms + [fn], self.name + suffix)

    def for_each(self, fn) -> "LocalIterator":
        return self._with(lambda it: map(fn, it), f".for_each({fn})")

    def filter(self, fn) -> "LocalIterator":
        return self._with(lambda it: filter(fn, it), f".filter({fn})")

    def batch(self, n: int) -> "LocalIterator":
        def batcher(it):
            batch = []
            for item in it:
                batch.append(item)
                if len(batch) >= n:
                    yield batch
                    batch = []
            if batch:
                yield batch
        return self._with(batcher, f".batch({n})")

    def flatten(self) -> "LocalIterator":
        def flattener(it):
            for item in it:
                yield from item
        return self._with(flattener, ".flatten()")

    def combine(self, fn) -> "LocalIterator":
        return self.for_each(fn).flatten()

    def shuffle(self, shuffle_buffer_size: int, seed=None) -> "LocalIterator":
        def shuffler(it):
            rng = random.Random(seed)
            buf = []
            for item in it:
                buf.append(item)
                if len(buf) >= shuffle_buffer_size:
                    yield buf.pop(rng.randrange(len(buf)))
            while buf:
                yield buf.pop(rng.randrange(len(buf)))
        return self._with(shuffler, ".shuffle()")

    def zip_with_source_actor(self):
        raise NotImplementedError(
            "zip_with_source_actor is not supported in ray_tpu")

    def take(self, n) -> List[T]:
        out = []
        for item in self:
            out.append(item)
            if len(out) >= n:
                break
        return out

    def show(self, n: int = 20) -> None:
        for item in self.take(n):
            print(item)

    def union(self, *others: "LocalIterator") -> "LocalIterator":
        iterators = [self] + list(others)

        def base(timeout=None):
            active = [iter(it) for it in iterators]
            while active:
                for it in list(active):
                    try:
                        yield next(it)
                    except StopIteration:
                        active.remove(it)
        return LocalIterator(base, name=f"union({len(iterators)})")

    def duplicate(self, n: int) -> List["LocalIterator"]:
        queues = [collections.deque() for _ in range(n)]
        source = iter(self)

        def make(i):
            def base(timeout=None):
                while True:
                    if queues[i]:
                        yield queues[i].popleft()
                        continue
                    try:
                        item = next(source)
                    except StopIteration:
                        if queues[i]:
                            continue
                        return
                    for q in queues:
                        q.append(item)
            return LocalIterator(base, name=self.name + f".dup[{i}]")
        return [make(i) for i in range(n)]
