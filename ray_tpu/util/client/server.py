"""Client server — hosts remote drivers (reference:
python/ray/util/client/server/server.py proxying each client onto the
cluster). One thread per connection; object refs cross the wire as
opaque ids held server-side per client (released on disconnect).
"""

from __future__ import annotations

import socketserver
import threading
import uuid
from typing import Any, Dict, Optional, Tuple

import ray_tpu
from ray_tpu.util.client.protocol import recv_msg, send_msg


def _make_remote(func_or_class, options):
    if options:
        return ray_tpu.remote(**options)(func_or_class)
    return ray_tpu.remote(func_or_class)


def _resolve_descriptor(name: str):
    """'module:attr' -> the named callable (cross-language descriptor)."""
    import importlib

    mod_name, _, attr = name.partition(":")
    return getattr(importlib.import_module(mod_name), attr)


class _ClientSession:
    """Server-side state for one connected client."""

    def __init__(self):
        self.refs: Dict[bytes, Any] = {}       # client ref id -> ObjectRef
        self.actors: Dict[bytes, Any] = {}     # client actor id -> handle
        self.funcs: Dict[bytes, Any] = {}      # func id -> RemoteFunction
        # non-Python clients (cpp/) can't unpickle exception objects;
        # init{"simple_errors": true} downgrades errors to repr strings
        self.simple_errors = False

    def track_ref(self, ref) -> bytes:
        rid = uuid.uuid4().bytes
        self.refs[rid] = ref
        return rid


class ClientServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 init_kwargs: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(**(init_kwargs or {}))
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                session = _ClientSession()
                try:
                    while True:
                        try:
                            msg = recv_msg(self.request)
                        except (ConnectionError, EOFError):
                            break
                        try:
                            reply = outer._dispatch(session, msg)
                        except BaseException as e:  # noqa: BLE001
                            reply = {"ok": False,
                                     "error": repr(e) if session.simple_errors
                                     else e}
                        try:
                            send_msg(self.request, reply)
                        except ValueError as e:
                            send_msg(self.request,
                                     {"ok": False, "error": e})
                finally:
                    session.refs.clear()
                    for handle in session.actors.values():
                        try:
                            ray_tpu.kill(handle)
                        except Exception:
                            pass

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"ray://127.0.0.1:{self.port}"

    def stop(self) -> None:
        self._server.shutdown()

    # --------------------------------------------------------------- ops
    def _dispatch(self, session: _ClientSession, msg: dict) -> dict:
        op = msg["op"]
        if op == "init":
            session.simple_errors = bool(msg.get("simple_errors"))
            return {"ok": True, "version": ray_tpu.__version__}
        if op == "put":
            ref = ray_tpu.put(msg["value"])
            return {"ok": True, "ref": session.track_ref(ref)}
        if op == "get":
            refs = [session.refs[r] for r in msg["refs"]]
            values = ray_tpu.get(refs, timeout=msg.get("timeout"))
            return {"ok": True, "values": values}
        if op == "wait":
            by_id = {rid: session.refs[rid] for rid in msg["refs"]}
            ready, unready = ray_tpu.wait(
                list(by_id.values()), num_returns=msg["num_returns"],
                timeout=msg.get("timeout"))
            ready_set = {id(r) for r in ready}
            return {"ok": True,
                    "ready": [rid for rid, r in by_id.items()
                              if id(r) in ready_set],
                    "unready": [rid for rid, r in by_id.items()
                                if id(r) not in ready_set]}
        if op == "task":
            fid = msg["func_id"]
            if fid not in session.funcs:
                session.funcs[fid] = _make_remote(
                    msg["func"], msg.get("options"))
            return self._submit_task(session, session.funcs[fid], msg)
        if op == "task_by_name":
            # Cross-language entry (reference: cross_language.py — Java/C++
            # callers name Python functions by module descriptor instead of
            # shipping pickled code): "module:attr", resolved server-side.
            options = msg.get("options") or {}
            # options are part of the identity: the same name with
            # different options must not reuse a cached wrapper
            key = b"name:" + repr((msg["name"], sorted(
                options.items()))).encode()
            if key not in session.funcs:
                session.funcs[key] = _make_remote(
                    _resolve_descriptor(msg["name"]), options)
            return self._submit_task(session, session.funcs[key], msg)
        if op in ("actor_create", "actor_create_by_name"):
            cls = (_resolve_descriptor(msg["name"])
                   if op == "actor_create_by_name" else msg["cls"])
            actor_cls = _make_remote(cls, msg.get("options"))
            args, kwargs = self._resolve(session, msg.get("args", ()),
                                         msg.get("kwargs", {}))
            handle = actor_cls.remote(*args, **kwargs)
            aid = uuid.uuid4().bytes
            session.actors[aid] = handle
            return {"ok": True, "actor_id": aid}
        if op == "actor_call":
            handle = session.actors[msg["actor_id"]]
            args, kwargs = self._resolve(session, msg["args"],
                                         msg["kwargs"])
            ref = getattr(handle, msg["method"]).remote(*args, **kwargs)
            return {"ok": True, "ref": session.track_ref(ref)}
        if op == "kill":
            no_restart = bool(msg.get("no_restart", True))
            # the handle stays in the session map in BOTH cases: after a
            # restartable kill it routes to the restarted incarnation,
            # and after a hard kill later calls surface ActorDiedError
            # exactly like the direct path (popping it here made them a
            # bare KeyError); disconnect cleanup tolerates dead handles
            handle = session.actors.get(msg["actor_id"])
            if handle is not None:
                ray_tpu.kill(handle, no_restart=no_restart)
            return {"ok": True}
        raise ValueError(f"unknown op {op!r}")

    def _submit_task(self, session: _ClientSession, remote_func,
                     msg: dict) -> dict:
        args, kwargs = self._resolve(session, msg.get("args", ()),
                                     msg.get("kwargs", {}))
        out = remote_func.remote(*args, **kwargs)
        refs = out if isinstance(out, list) else [out]
        return {"ok": True,
                "refs": [session.track_ref(r) for r in refs],
                "single": not isinstance(out, list)}

    def _resolve(self, session: _ClientSession, args, kwargs
                 ) -> Tuple[tuple, dict]:
        def r(v):
            if isinstance(v, dict) and v.get("__client_ref__") is not None:
                return session.refs[v["__client_ref__"]]
            return v

        return tuple(r(a) for a in args), {k: r(v)
                                           for k, v in kwargs.items()}


def main(argv=None) -> None:
    """Standalone client server process (the reference's `ray start
    --ray-client-server-port` role): hosts an in-process runtime and
    serves ray:// drivers."""
    import argparse
    import json
    import threading

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--init-kwargs", default="{}",
                        help="JSON kwargs for ray_tpu.init")
    args = parser.parse_args(argv)
    server = ClientServer(args.host, args.port,
                          init_kwargs=json.loads(args.init_kwargs))
    print(f"CLIENT_SERVER_ADDRESS {server.address}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
