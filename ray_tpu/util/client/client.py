"""Client-side remote driver (reference: python/ray/util/client/
__init__.py RayAPIStub + worker.py): connect with
``ray_tpu.util.client.connect("ray://host:port")`` and use the familiar
remote/get/put/wait surface; code ships to the server as cloudpickle.
"""

from __future__ import annotations

import socket
import threading
import uuid
from typing import Any, List, Optional, Union

from ray_tpu.util.client.protocol import recv_msg, send_msg


class ClientObjectRef:
    def __init__(self, rid: bytes, client: "ClientContext"):
        self._rid = rid
        self._client = client

    def __reduce_ex__(self, proto):
        raise TypeError(
            "ClientObjectRef can only be used as a direct task argument")

    def _wire(self) -> dict:
        return {"__client_ref__": self._rid}


def _encode_args(args, kwargs):
    def enc(v):
        return v._wire() if isinstance(v, ClientObjectRef) else v

    return tuple(enc(a) for a in args), {k: enc(v)
                                         for k, v in kwargs.items()}


class ClientRemoteFunction:
    def __init__(self, client: "ClientContext", func, options:
                 Optional[dict] = None):
        self._client = client
        self._func = func
        self._options = options or {}
        self._func_id = uuid.uuid4().bytes

    def options(self, **overrides) -> "ClientRemoteFunction":
        return ClientRemoteFunction(self._client, self._func,
                                    {**self._options, **overrides})

    def remote(self, *args, **kwargs):
        wire_args, wire_kwargs = _encode_args(args, kwargs)
        reply = self._client._request({
            "op": "task", "func": self._func, "func_id": self._func_id,
            "options": self._options,
            "args": wire_args, "kwargs": wire_kwargs})
        refs = [ClientObjectRef(r, self._client) for r in reply["refs"]]
        return refs[0] if reply["single"] else refs


class ClientActorMethod:
    def __init__(self, handle: "ClientActorHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        wire_args, wire_kwargs = _encode_args(args, kwargs)
        reply = self._handle._client._request({
            "op": "actor_call", "actor_id": self._handle._actor_id,
            "method": self._method,
            "args": wire_args, "kwargs": wire_kwargs})
        return ClientObjectRef(reply["ref"], self._handle._client)


class ClientActorHandle:
    def __init__(self, client: "ClientContext", actor_id: bytes):
        self._client = client
        self._actor_id = actor_id

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return ClientActorMethod(self, item)


class ClientActorClass:
    def __init__(self, client: "ClientContext", cls,
                 options: Optional[dict] = None):
        self._client = client
        self._cls = cls
        self._options = options or {}

    def options(self, **overrides) -> "ClientActorClass":
        return ClientActorClass(self._client, self._cls,
                                {**self._options, **overrides})

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        wire_args, wire_kwargs = _encode_args(args, kwargs)
        reply = self._client._request({
            "op": "actor_create", "cls": self._cls,
            "options": self._options,
            "args": wire_args, "kwargs": wire_kwargs})
        return ClientActorHandle(self._client, reply["actor_id"])


class ClientContext:
    def __init__(self, address: str):
        if address.startswith("ray://"):
            address = address[len("ray://"):]
        host, _, port = address.rpartition(":")
        self._sock = socket.create_connection((host or "127.0.0.1",
                                               int(port)), timeout=60)
        self._lock = threading.Lock()
        reply = self._request({"op": "init"})
        self.server_version = reply["version"]
        self.connected = True

    def _request(self, msg: dict) -> dict:
        with self._lock:
            send_msg(self._sock, msg)
            reply = recv_msg(self._sock)
        if not reply.get("ok"):
            raise reply.get("error", RuntimeError("client request failed"))
        return reply

    # -------------------------------------------------------- ray surface
    def remote(self, obj=None, **options):
        import inspect

        def wrap(o):
            if inspect.isclass(o):
                return ClientActorClass(self, o, options)
            return ClientRemoteFunction(self, o, options)

        if obj is not None:
            return wrap(obj)
        return wrap

    def put(self, value: Any) -> ClientObjectRef:
        reply = self._request({"op": "put", "value": value})
        return ClientObjectRef(reply["ref"], self)

    def get(self, refs: Union[ClientObjectRef, List[ClientObjectRef]],
            timeout: Optional[float] = None):
        single = isinstance(refs, ClientObjectRef)
        ref_list = [refs] if single else list(refs)
        reply = self._request({
            "op": "get", "refs": [r._rid for r in ref_list],
            "timeout": timeout})
        return reply["values"][0] if single else reply["values"]

    def wait(self, refs: List[ClientObjectRef], *, num_returns: int = 1,
             timeout: Optional[float] = None):
        reply = self._request({
            "op": "wait", "refs": [r._rid for r in refs],
            "num_returns": num_returns, "timeout": timeout})
        by_id = {r._rid: r for r in refs}
        return ([by_id[r] for r in reply["ready"]],
                [by_id[r] for r in reply["unready"]])

    def kill(self, handle: ClientActorHandle,
             no_restart: bool = True) -> None:
        self._request({"op": "kill", "actor_id": handle._actor_id,
                       "no_restart": no_restart})

    def disconnect(self) -> None:
        if self.connected:
            self._sock.close()
            self.connected = False


def connect(address: str) -> ClientContext:
    return ClientContext(address)
