"""Wire protocol for the client (reference: src/ray/protobuf/
ray_client.proto + python/ray/util/client/ARCHITECTURE.md — the real one
is gRPC; here it is length-prefixed cloudpickle frames over TCP, which
keeps the same request/response shapes without a protobuf toolchain).

Requests: {"op": <str>, ...}; responses: {"ok": bool, ...}.
Ops: init, put, get, wait, task (submit), actor_create, actor_call,
kill, shutdown.
"""

from __future__ import annotations

import socket
import struct
from typing import Any

try:
    import cloudpickle as pickle
except ImportError:  # pragma: no cover
    import pickle

_LEN = struct.Struct("!Q")
MAX_FRAME = 1 << 31


def send_msg(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj)
    if len(payload) > MAX_FRAME:
        raise ValueError("frame too large")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket) -> Any:
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError("frame too large")
    return pickle.loads(_recv_exact(sock, length))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise ConnectionError("peer closed")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)
