"""ray_tpu.util.client — remote driver over a socket.

Reference surface: python/ray/util/client/ (ray://-address drivers
proxied through a server onto the cluster).
"""

from ray_tpu.util.client.client import (  # noqa: F401
    ClientActorHandle,
    ClientContext,
    ClientObjectRef,
    connect,
)
from ray_tpu.util.client.server import ClientServer  # noqa: F401

__all__ = ["connect", "ClientContext", "ClientServer", "ClientObjectRef",
           "ClientActorHandle"]
