"""User-facing custom metrics API.

Reference: python/ray/util/metrics.py — applications define
Counter/Gauge/Histogram that flow into the same registry the system
metrics use and out through the Prometheus endpoint / dashboard. The
classes ARE the observability registry's metric types; this module is
the public alias the reference places them under.

    from ray_tpu.util.metrics import Counter

    requests = Counter("app_requests", description="requests served",
                       tag_keys=("route",))
    requests.inc(tags={"route": "/predict"})
"""

from ray_tpu.observability.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
)

__all__ = ["Counter", "Gauge", "Histogram"]
