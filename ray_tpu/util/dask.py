"""Dask-on-ray_tpu scheduler shim.

Reference: python/ray/util/dask/ — a dask ``get`` scheduler that runs
each dask-graph task as a Ray task, so ``dask.compute(...,
scheduler=ray_dask_get)`` executes on the cluster with inter-task
data flowing through the object store.

A dask graph is plain data (the library is NOT required here): a dict
mapping keys to computations, where a computation is

    (callable, arg, ...)   a task; args may be keys, literals, or
                           nested task tuples
    key                    an alias of another graph entry
    literal                a constant

``ray_dask_get(dsk, keys)`` matches dask's scheduler ``get`` contract
(dask/core.py get): pass it to ``dask.compute``/``.compute(scheduler=
ray_dask_get)`` when dask is installed; the test suite drives it with
raw graph dicts since this image ships no dask.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

import ray_tpu

_exec_remote = None


def _executor():
    """Lazy: ray_tpu.remote at import time would bind before init."""
    global _exec_remote
    if _exec_remote is None:
        @ray_tpu.remote
        def _dask_node(func, spec, *dep_values):
            # deps arrive positionally; rebuild the arg structure with
            # nested sub-tasks evaluated locally
            env = dict(zip(spec, dep_values))

            def rebuild(a):
                if isinstance(a, tuple) and a and callable(a[0]):
                    return a[0](*[rebuild(x) for x in a[1:]])
                if isinstance(a, list):
                    return [rebuild(x) for x in a]
                try:
                    if a in env:  # tuple keys may hold unhashables
                        return env[a]
                except TypeError:
                    pass
                return a

            return func(*[rebuild(a) for a in spec["__args__"]])

        _exec_remote = _dask_node
    return _exec_remote


def _task_deps(comp: Any, dsk: Dict) -> List[Hashable]:
    """Keys of ``dsk`` referenced anywhere inside a computation."""
    deps: List[Hashable] = []

    def walk(a):
        if isinstance(a, tuple) and a and callable(a[0]):
            for x in a[1:]:
                walk(x)
        elif isinstance(a, list):
            for x in a:
                walk(x)
        else:
            try:
                if a in dsk:
                    deps.append(a)
            except TypeError:
                pass

    if isinstance(comp, tuple) and comp and callable(comp[0]):
        for a in comp[1:]:
            walk(a)
    else:
        try:
            if comp in dsk:
                deps.append(comp)
        except TypeError:
            pass
    return deps


def ray_dask_get(dsk: Dict, keys, **kwargs):
    """Execute a dask graph on the cluster; returns values matching the
    (possibly nested) structure of ``keys``."""
    refs: Dict[Hashable, Any] = {}

    def schedule(key) -> Any:
        if key in refs:
            return refs[key]
        comp = dsk[key]
        if isinstance(comp, tuple) and comp and callable(comp[0]):
            dep_keys = _task_deps(comp, dsk)
            dep_refs = [schedule(k) for k in dep_keys]
            spec = {k: None for k in dep_keys}
            spec["__args__"] = list(comp[1:])
            ref = _executor().remote(comp[0], spec, *dep_refs)
        else:
            is_alias = False
            try:
                is_alias = comp in dsk
            except TypeError:
                pass
            ref = schedule(comp) if is_alias else ray_tpu.put(comp)
        refs[key] = ref
        return ref

    def resolve(ks):
        if isinstance(ks, list):
            return [resolve(k) for k in ks]
        return ray_tpu.get(schedule(ks))

    return resolve(keys)
