"""joblib backend on the actor Pool.

Mirrors the reference's ray.util.joblib (python/ray/util/joblib/
__init__.py + ray_backend.py): ``register_ray()`` installs a "ray"
parallel backend so ``joblib.Parallel(backend="ray")`` fans out over
actors.
"""

from __future__ import annotations


def register_ray() -> None:
    try:
        from joblib import register_parallel_backend
        from joblib._parallel_backends import MultiprocessingBackend
    except ImportError as e:  # joblib not in the image — gate cleanly
        raise ImportError(
            "joblib is required for register_ray(); it is not installed"
        ) from e

    from ray_tpu.util.multiprocessing import Pool

    class RayBackend(MultiprocessingBackend):
        """joblib backend whose worker pool is ray_tpu actors."""

        supports_timeout = True

        def effective_n_jobs(self, n_jobs):
            import ray_tpu

            if not ray_tpu.is_initialized():
                ray_tpu.init()
            if n_jobs is None or n_jobs == -1:
                n_jobs = int(ray_tpu.cluster_resources().get("CPU", 1))
            return max(1, n_jobs)

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **memmapping_opts):
            n_jobs = self.effective_n_jobs(n_jobs)
            self._pool = Pool(processes=n_jobs)
            self.parallel = parallel
            return n_jobs

        def terminate(self):
            if getattr(self, "_pool", None) is not None:
                self._pool.terminate()
                self._pool = None

    register_parallel_backend("ray", RayBackend)
