"""Python -> C++ task execution (the reverse of the C++ client).

Reference: cpp/src/ray/worker/default_worker.cc — a native worker
registers C++ functions and executes tasks other languages submit.
Here the native worker is ``cpp/build/cpp_worker`` (cpp/src/worker.cpp
execution loop over the framed-pickle wire); this module spawns it,
scrapes its ``CPP_WORKER_ADDRESS`` announce line, and exposes each
registered C++ function as a ``.remote()``-able task. The submitted
ray_tpu task is a thin transport shim (the cross-language boundary,
like the reference's core-worker RPC hop); the COMPUTE runs in the
native worker process.

    worker = start_cpp_worker()
    fib = worker.remote_function("fib")
    ray_tpu.get(fib.remote(20))   # == 6765, computed in C++
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import subprocess
from typing import Any, List, Optional

import ray_tpu

_LEN = struct.Struct("!Q")


def _rpc(address: str, request: dict) -> Any:
    """One round-trip on the native worker's wire."""
    host, _, port = address.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=30.0) as s:
        payload = pickle.dumps(request)
        s.sendall(_LEN.pack(len(payload)) + payload)
        header = _recv_exact(s, 8)
        reply = pickle.loads(_recv_exact(s, _LEN.unpack(header)[0]))
    if not reply.get("ok"):
        raise CrossLanguageError(reply.get("error", "unknown error"))
    return reply.get("value")


def _call_cpp(address: str, func: str, args: List[Any]) -> Any:
    return _rpc(address, {"op": "execute", "func": func,
                          "args": list(args)})


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("cpp worker closed the connection")
        buf += chunk
    return buf


class CrossLanguageError(RuntimeError):
    pass


class CppFunction:
    """A registered C++ function as a remote-callable: ``.remote()``
    submits a ray_tpu task that forwards to the native worker (so the
    call composes with refs/retries like any task), ``.call()`` invokes
    synchronously."""

    def __init__(self, address: str, name: str):
        self.address = address
        self.name = name
        self._remote_fn = ray_tpu.remote(
            lambda address, func, args: _call_cpp(address, func, args))

    def call(self, *args):
        return _call_cpp(self.address, self.name, list(args))

    def remote(self, *args):
        return self._remote_fn.remote(self.address, self.name, list(args))


class CppWorkerHandle:
    def __init__(self, proc: Optional[subprocess.Popen], address: str):
        self.proc = proc
        self.address = address

    def remote_function(self, name: str) -> CppFunction:
        return CppFunction(self.address, name)

    def list_functions(self) -> List[str]:
        return list(_rpc(self.address, {"op": "list"}))

    def ping(self) -> bool:
        return _rpc(self.address, {"op": "ping"}) == "pong"

    def close(self) -> None:
        try:
            _rpc(self.address, {"op": "shutdown"})
        except Exception:  # noqa: BLE001 — already gone
            pass
        if self.proc is not None:
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()


def default_worker_binary() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "cpp", "build", "cpp_worker")


def start_cpp_worker(binary: Optional[str] = None,
                     timeout_s: float = 30.0) -> CppWorkerHandle:
    """Spawn the native worker and scrape its announce line (the same
    contract every server process in this framework uses)."""
    import select
    import time

    binary = binary or default_worker_binary()
    proc = subprocess.Popen([binary], stdout=subprocess.PIPE, text=True)
    deadline = time.monotonic() + timeout_s
    os.set_blocking(proc.stdout.fileno(), False)
    buf = ""
    try:
        while time.monotonic() < deadline:
            # select-bounded read: a worker that never prints must FAIL
            # at the deadline, not park the caller in readline()
            ready, _, _ = select.select(
                [proc.stdout], [], [],
                max(0.0, deadline - time.monotonic()))
            if not ready:
                continue
            chunk = proc.stdout.read()
            if chunk == "" and proc.poll() is not None:
                raise RuntimeError(
                    f"cpp worker exited rc={proc.poll()} before "
                    "announcing")
            buf += chunk or ""
            for line in buf.splitlines():
                if line.startswith("CPP_WORKER_ADDRESS"):
                    return CppWorkerHandle(proc, line.split()[1])
        raise RuntimeError("cpp worker never announced its address")
    except BaseException:
        proc.kill()
        raise


def connect_cpp_worker(address: str) -> CppWorkerHandle:
    """Attach to an already-running native worker."""
    return CppWorkerHandle(None, address)
