"""Distributed tracing: spans around task/actor submission & execution.

Reference: python/ray/util/tracing/tracing_helper.py — OpenTelemetry
spans are wrapped around ``.remote()`` invocation
(_tracing_task_invocation:286) and worker-side execution
(_inject_tracing_into_function:320), with the span context propagated
*inside the task spec* so the execution span parents to the submission
span across the process boundary. Opt-in via
``ray.init(_tracing_startup_hook=...)`` (worker.py:666).

This build keeps the same shape without requiring the opentelemetry
package: a minimal tracer with W3C-style ids, context carried in
``TaskSpec.trace_context`` and on every RPC frame (the ``_trace``
reserved kwarg, cluster/rpc.py), and pluggable exporters (the default
buffers in memory; ``JsonFileExporter`` mirrors the reference's
setup_local_tmp_tracing hook which exports spans to a local file).

Sampling is head-based: the decision is made once at the trace root —
from the seeded fault-plane RNG so runs replay deterministically
(raycheck RC03) — and rides the wire with the context, so a trace is
recorded everywhere or nowhere. Server processes that never called
``setup_tracing`` still record handler spans for sampled remote traces
via :func:`record_remote_span`; those land in the bounded span buffer
and the per-process flight recorder, which is how `cli.py timeline`
stitches a whole-cluster trace together.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

_state = threading.local()
_lock = threading.Lock()
_enabled = False
_exporters: List[Callable[["Span"], None]] = []
_MAX_BUFFER = 100_000
# Bounded: long-lived processes keep the most recent spans only, and the
# counter keeps dumps honest about evicted history (raycheck RC10).
_buffer: deque = deque(maxlen=_MAX_BUFFER)
_dropped = 0
_sampler_rng = None


@dataclass
class SpanContext:
    trace_id: str
    span_id: str
    sampled: bool = True

    def to_dict(self) -> Dict[str, str]:
        """Wire form (the RPC ``_trace`` kwarg / TaskSpec.trace_context):
        string values only, so the frame stays schema-friendly."""
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "sampled": "1" if self.sampled else "0"}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, str]]
                  ) -> Optional["SpanContext"]:
        if not d:
            return None
        return cls(d["trace_id"], d["span_id"],
                   str(d.get("sampled", "1")) not in ("0", "False",
                                                      "false"))


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_time: float
    end_time: Optional[float] = None
    attributes: Dict[str, Any] = field(default_factory=dict)
    events: List[Dict[str, Any]] = field(default_factory=list)
    status: str = "OK"
    sampled: bool = True

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, attributes: Optional[dict] = None
                  ) -> None:
        self.events.append({"name": name, "time": time.time(),
                            "attributes": attributes or {}})

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, self.sampled)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "trace_id": self.trace_id,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "start_time": self.start_time, "end_time": self.end_time,
            "duration_ms": None if self.end_time is None
            else (self.end_time - self.start_time) * 1e3,
            "attributes": self.attributes, "events": self.events,
            "status": self.status,
        }


@contextmanager
def maybe_span(name_fn, parent=None, attributes_fn=None, on_span=None):
    """No-op context when tracing is off; otherwise opens a span.

    ``name_fn``/``attributes_fn`` are thunks so hot paths don't pay
    f-string/hex construction for disabled tracing; ``on_span`` (if
    given) receives the live span — call sites use it to stamp
    spec.trace_context."""
    if not _enabled:
        yield None
        return
    with start_span(name_fn(),
                    parent=parent,
                    attributes=attributes_fn() if attributes_fn else None
                    ) as span:
        if span is not None and on_span is not None:
            on_span(span)
        yield span


# ----------------------------------------------------------------- control
def enabled() -> bool:
    """Hot-path guard: callers skip span construction entirely when off."""
    return _enabled


def setup_tracing(exporter: Optional[Callable[[Span], None]] = None) -> None:
    """Enable tracing (reference: _tracing_startup_hook). Idempotent;
    extra exporters accumulate."""
    global _enabled
    _enabled = True
    if exporter is not None:
        with _lock:
            _exporters.append(exporter)


def shutdown_tracing() -> None:
    global _enabled, _dropped
    _enabled = False
    with _lock:
        _exporters.clear()
        _buffer.clear()
        _dropped = 0
    _state.current = None
    reset_sampling()


def is_tracing_enabled() -> bool:
    return _enabled


def get_buffered_spans() -> List[Span]:
    with _lock:
        return list(_buffer)


def get_dropped_spans() -> int:
    """Spans evicted from the bounded buffer since the last reset."""
    with _lock:
        return _dropped


# --------------------------------------------------------------- sampling
def _sample() -> bool:
    """Head-based sampling decision, made once per trace at the root.

    Seeded through fault_plane.derive_rng so a RAY_TPU_FAULT_PLAN seed
    replays the exact same sample set (raycheck RC03: no unseeded
    randomness on control paths)."""
    from ray_tpu._private.config import Config
    rate = Config.instance().tracing_sample_rate
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    global _sampler_rng
    with _lock:
        if _sampler_rng is None:
            from ray_tpu.cluster import fault_plane
            _sampler_rng = fault_plane.derive_rng("tracing-sample")
        return _sampler_rng.random() < rate


def reset_sampling() -> None:
    """Drop the sampler RNG so the next decision re-derives it from the
    current fault-plane seed (tests replay decision sequences)."""
    global _sampler_rng
    with _lock:
        _sampler_rng = None


class JsonFileExporter:
    """Append finished spans to a JSON-lines file (reference:
    setup_local_tmp_tracing.py exports to a local tmp dir)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()

    def __call__(self, span: Span) -> None:
        with self._lock, open(self.path, "a") as f:
            f.write(json.dumps(span.to_dict(), default=str) + "\n")


# ------------------------------------------------------------------- spans
def current_context() -> Optional[SpanContext]:
    span = getattr(_state, "current", None)
    return span.context() if span is not None else None


@contextmanager
def start_span(name: str, parent: Optional[SpanContext] = None,
               attributes: Optional[dict] = None):
    """Yields a live Span (or None when tracing is off, so call sites can
    stay unconditional).

    A root span (no parent anywhere) draws the head-based sampling
    decision; children inherit it. Unsampled spans still flow through
    the thread-local so the negative decision propagates to the wire,
    but they are never buffered or exported."""
    if not _enabled:
        yield None
        return
    if parent is None:
        parent = current_context()
    if parent is not None:
        sampled = parent.sampled
    else:
        sampled = _sample()
    span = Span(
        name=name,
        trace_id=parent.trace_id if parent else os.urandom(16).hex(),
        span_id=os.urandom(8).hex(),
        parent_id=parent.span_id if parent else None,
        start_time=time.time(),
        attributes=dict(attributes or {}),
        sampled=sampled,
    )
    prev = getattr(_state, "current", None)
    _state.current = span
    try:
        yield span
    except BaseException as e:
        span.status = f"ERROR: {type(e).__name__}"
        raise
    finally:
        span.end_time = time.time()
        _state.current = prev
        if sampled:
            _export(span)


def record_remote_span(name: str, wire: Optional[Dict[str, str]],
                       start_time: float, end_time: float,
                       queue_wait_s: Optional[float] = None,
                       attributes: Optional[dict] = None,
                       status: str = "OK") -> Optional[Span]:
    """Record a server-side span parented to a wire ``_trace`` context.

    Server processes never call setup_tracing, so this bypasses the
    ``_enabled`` gate: any process touched by a *sampled* trace records
    its handler spans into the bounded buffer + flight recorder, which
    is what makes the merged cluster timeline possible. Returns the
    span (callers can stamp more attributes) or None when the wire
    context is absent/unsampled."""
    ctx = SpanContext.from_dict(wire)
    if ctx is None or not ctx.sampled:
        return None
    attrs = dict(attributes or {})
    if queue_wait_s is not None:
        attrs["queue_wait_ms"] = queue_wait_s * 1e3
    span = Span(
        name=name,
        trace_id=ctx.trace_id,
        span_id=os.urandom(8).hex(),
        parent_id=ctx.span_id,
        start_time=start_time,
        end_time=end_time,
        attributes=attrs,
        status=status,
    )
    _export(span)
    return span


def record_span_tree(root_name: str, wall_start: float,
                     children, attributes: Optional[dict] = None) -> None:
    """Record a completed root span plus sequential child spans from
    ``(name, duration_s)`` pairs — the scheduler tick anatomy: one
    ``scheduler.tick`` span whose children are the named phases laid
    end to end from ``wall_start``. No-op when tracing is off or the
    current trace is unsampled."""
    if not _enabled:
        return
    with start_span(root_name, attributes=attributes) as root:
        if root is None or not root.sampled:
            return
        root.start_time = wall_start
        t = wall_start
        for name, dur in children:
            child = Span(name=name, trace_id=root.trace_id,
                         span_id=os.urandom(8).hex(),
                         parent_id=root.span_id,
                         start_time=t, end_time=t + dur)
            t += dur
            _export(child)


def _export(span: Span) -> None:
    global _dropped
    with _lock:
        if len(_buffer) == _buffer.maxlen:
            _dropped += 1
        _buffer.append(span)
        exporters = list(_exporters)
    try:
        from ray_tpu._private.config import Config
        if Config.instance().observability_plane_enabled:
            from ray_tpu.observability import flight_recorder
            flight_recorder.global_recorder.record_span(span.to_dict())
    except Exception:
        pass
    for exp in exporters:
        try:
            exp(span)
        except Exception:
            pass
