"""Distributed tracing: spans around task/actor submission & execution.

Reference: python/ray/util/tracing/tracing_helper.py — OpenTelemetry
spans are wrapped around ``.remote()`` invocation
(_tracing_task_invocation:286) and worker-side execution
(_inject_tracing_into_function:320), with the span context propagated
*inside the task spec* so the execution span parents to the submission
span across the process boundary. Opt-in via
``ray.init(_tracing_startup_hook=...)`` (worker.py:666).

This build keeps the same shape without requiring the opentelemetry
package: a minimal tracer with W3C-style ids, context carried in
``TaskSpec.trace_context``, and pluggable exporters (the default buffers
in memory; ``JsonFileExporter`` mirrors the reference's
setup_local_tmp_tracing hook which exports spans to a local file).
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

_state = threading.local()
_lock = threading.Lock()
_enabled = False
_exporters: List[Callable[["Span"], None]] = []
_buffer: List["Span"] = []
_MAX_BUFFER = 100_000


@dataclass
class SpanContext:
    trace_id: str
    span_id: str

    def to_dict(self) -> Dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, str]]
                  ) -> Optional["SpanContext"]:
        if not d:
            return None
        return cls(d["trace_id"], d["span_id"])


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_time: float
    end_time: Optional[float] = None
    attributes: Dict[str, Any] = field(default_factory=dict)
    events: List[Dict[str, Any]] = field(default_factory=list)
    status: str = "OK"

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, attributes: Optional[dict] = None
                  ) -> None:
        self.events.append({"name": name, "time": time.time(),
                            "attributes": attributes or {}})

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "trace_id": self.trace_id,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "start_time": self.start_time, "end_time": self.end_time,
            "duration_ms": None if self.end_time is None
            else (self.end_time - self.start_time) * 1e3,
            "attributes": self.attributes, "events": self.events,
            "status": self.status,
        }


@contextmanager
def maybe_span(name_fn, parent=None, attributes_fn=None, on_span=None):
    """No-op context when tracing is off; otherwise opens a span.

    ``name_fn``/``attributes_fn`` are thunks so hot paths don't pay
    f-string/hex construction for disabled tracing; ``on_span`` (if
    given) receives the live span — call sites use it to stamp
    spec.trace_context."""
    if not _enabled:
        yield None
        return
    with start_span(name_fn(),
                    parent=parent,
                    attributes=attributes_fn() if attributes_fn else None
                    ) as span:
        if span is not None and on_span is not None:
            on_span(span)
        yield span


# ----------------------------------------------------------------- control
def enabled() -> bool:
    """Hot-path guard: callers skip span construction entirely when off."""
    return _enabled


def setup_tracing(exporter: Optional[Callable[[Span], None]] = None) -> None:
    """Enable tracing (reference: _tracing_startup_hook). Idempotent;
    extra exporters accumulate."""
    global _enabled
    _enabled = True
    if exporter is not None:
        with _lock:
            _exporters.append(exporter)


def shutdown_tracing() -> None:
    global _enabled
    _enabled = False
    with _lock:
        _exporters.clear()
        _buffer.clear()
    _state.current = None


def is_tracing_enabled() -> bool:
    return _enabled


def get_buffered_spans() -> List[Span]:
    with _lock:
        return list(_buffer)


class JsonFileExporter:
    """Append finished spans to a JSON-lines file (reference:
    setup_local_tmp_tracing.py exports to a local tmp dir)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()

    def __call__(self, span: Span) -> None:
        with self._lock, open(self.path, "a") as f:
            f.write(json.dumps(span.to_dict(), default=str) + "\n")


# ------------------------------------------------------------------- spans
def current_context() -> Optional[SpanContext]:
    span = getattr(_state, "current", None)
    return span.context() if span is not None else None


@contextmanager
def start_span(name: str, parent: Optional[SpanContext] = None,
               attributes: Optional[dict] = None):
    """Yields a live Span (or None when tracing is off, so call sites can
    stay unconditional)."""
    if not _enabled:
        yield None
        return
    if parent is None:
        parent = current_context()
    span = Span(
        name=name,
        trace_id=parent.trace_id if parent else os.urandom(16).hex(),
        span_id=os.urandom(8).hex(),
        parent_id=parent.span_id if parent else None,
        start_time=time.time(),
        attributes=dict(attributes or {}),
    )
    prev = getattr(_state, "current", None)
    _state.current = span
    try:
        yield span
    except BaseException as e:
        span.status = f"ERROR: {type(e).__name__}"
        raise
    finally:
        span.end_time = time.time()
        _state.current = prev
        _export(span)


def _export(span: Span) -> None:
    with _lock:
        if len(_buffer) < _MAX_BUFFER:
            _buffer.append(span)
        exporters = list(_exporters)
    for exp in exporters:
        try:
            exp(span)
        except Exception:
            pass
