"""ActorPool — borrow/submit over a fixed fleet of actors.

Mirrors the reference's ray.util.actor_pool.ActorPool
(python/ray/util/actor_pool.py): submit/get_next/get_next_unordered/map/
map_unordered plus push/pop_idle for fleet surgery.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional, TypeVar

import ray_tpu

V = TypeVar("V")


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle_actors: List[Any] = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: List[tuple] = []

    def map(self, fn: Callable[[Any, V], Any], values: Iterable[V]):
        """Ordered map over the pool; yields results in submission order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, V], Any],
                      values: Iterable[V]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def submit(self, fn: Callable[[Any, V], Any], value: V) -> None:
        if self._idle_actors:
            actor = self._idle_actors.pop()
            future = fn(actor, value)
            future_key = tuple(future) if isinstance(future, list) else future
            self._future_to_actor[future_key] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in submission order."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        if self._next_return_index >= self._next_task_index:
            raise ValueError("It is not allowed to call get_next() after "
                             "get_next_unordered()")
        future = self._index_to_future[self._next_return_index]
        if timeout is not None:
            res, _ = ray_tpu.wait([future], timeout=timeout)
            if not res:
                raise TimeoutError("Timed out waiting for result")
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        future_key = tuple(future) if isinstance(future, list) else future
        _, actor = self._future_to_actor.pop(future_key)
        self._return_actor(actor)
        return ray_tpu.get(future)

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Earliest-finishing result, any order."""
        if not self.has_next():
            raise StopIteration("No more results to get")
        res, _ = ray_tpu.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout)
        if res:
            [future] = res
        else:
            raise TimeoutError("Timed out waiting for result")
        i, actor = self._future_to_actor.pop(future)
        self._return_actor(actor)
        del self._index_to_future[i]
        self._next_return_index = max(self._next_return_index, i + 1)
        return ray_tpu.get(future)

    def _return_actor(self, actor: Any) -> None:
        self._idle_actors.append(actor)
        while self._pending_submits and self._idle_actors:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def has_free(self) -> bool:
        return bool(self._idle_actors) and not self._pending_submits

    def pop_idle(self) -> Optional[Any]:
        if self.has_free():
            return self._idle_actors.pop()
        return None

    def push(self, actor: Any) -> None:
        busy_actors = [a for _, a in self._future_to_actor.values()]
        if actor in self._idle_actors or actor in busy_actors:
            raise ValueError("Actor already belongs to current ActorPool")
        self._return_actor(actor)
