"""multiprocessing.Pool drop-in on actors.

Mirrors the reference's ray.util.multiprocessing.Pool
(python/ray/util/multiprocessing/pool.py): apply/apply_async/map/
map_async/imap/imap_unordered/starmap over a fleet of PoolActor actors,
with AsyncResult futures.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class TimeoutError(Exception):  # noqa: A001 — mirrors mp.TimeoutError
    pass


class _PoolActor:
    def __init__(self, initializer=None, initargs=None):
        if initializer:
            initializer(*(initargs or ()))

    def ping(self):
        return "pong"

    def run_batch(self, func, batch):
        results = []
        for args, kwargs in batch:
            results.append(func(*args, **(kwargs or {})))
        return results


class AsyncResult:
    def __init__(self, refs: List[Any], single: bool = False,
                 callback: Optional[Callable] = None,
                 error_callback: Optional[Callable] = None):
        self._refs = refs
        self._single = single
        self._callback = callback
        self._error_callback = error_callback
        self._result = None
        self._error = None
        self._done = False
        self._lock = threading.Lock()

    def _collect(self, timeout: Optional[float] = None):
        with self._lock:
            if self._done:
                return
            deadline = (time.monotonic() + timeout
                        if timeout is not None else None)
            try:
                chunks = []
                for ref in self._refs:
                    t = (max(0.0, deadline - time.monotonic())
                         if deadline else None)
                    ready, _ = ray_tpu.wait([ref], timeout=t)
                    if not ready:
                        raise TimeoutError("result not ready")
                    chunks.append(ray_tpu.get(ref))
                flat = list(itertools.chain.from_iterable(chunks))
                self._result = flat[0] if self._single else flat
                if self._callback:
                    self._callback(self._result)
            except TimeoutError:
                raise
            except Exception as e:  # noqa: BLE001
                self._error = e
                if self._error_callback:
                    self._error_callback(e)
            self._done = True

    def get(self, timeout: Optional[float] = None):
        self._collect(timeout)
        if self._error is not None:
            raise self._error
        return self._result

    def wait(self, timeout: Optional[float] = None) -> None:
        try:
            self._collect(timeout)
        except TimeoutError:
            pass

    def ready(self) -> bool:
        if self._done:
            return True
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self._done:
            raise ValueError("Result is not ready")
        return self._error is None


class Pool:
    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: Optional[tuple] = None,
                 maxtasksperchild: Optional[int] = None,
                 ray_address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._processes = processes or self._default_processes()
        if self._processes < 1:
            raise ValueError("processes must be >= 1")
        self._actor_cls = ray_tpu.remote(_PoolActor)
        self._actors = [
            self._actor_cls.remote(initializer, initargs)
            for _ in range(self._processes)]
        ray_tpu.get([a.ping.remote() for a in self._actors])
        self._rr = itertools.cycle(range(self._processes))
        self._closed = False

    @staticmethod
    def _default_processes() -> int:
        total = ray_tpu.cluster_resources().get("CPU")
        return int(total) if total else (os.cpu_count() or 1)

    def _check_running(self):
        if self._closed:
            raise ValueError("Pool not running")

    # ------------------------------------------------------------- apply
    def apply(self, func, args=None, kwargs=None):
        return self.apply_async(func, args, kwargs).get()

    def apply_async(self, func, args=None, kwargs=None, callback=None,
                    error_callback=None) -> AsyncResult:
        self._check_running()
        actor = self._actors[next(self._rr)]
        ref = actor.run_batch.remote(func, [(args or (), kwargs or {})])
        return AsyncResult([ref], single=True, callback=callback,
                           error_callback=error_callback)

    # --------------------------------------------------------------- map
    def _chunk(self, iterable, chunksize: Optional[int], star: bool):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        batches = []
        for i in range(0, len(items), chunksize):
            batch = [((it if star else (it,)), {})
                     for it in items[i:i + chunksize]]
            batches.append(batch)
        return batches

    def _map_async(self, func, iterable, chunksize=None, star=False,
                   callback=None, error_callback=None) -> AsyncResult:
        self._check_running()
        refs = []
        for i, batch in enumerate(self._chunk(iterable, chunksize, star)):
            actor = self._actors[i % self._processes]
            refs.append(actor.run_batch.remote(func, batch))
        return AsyncResult(refs, callback=callback,
                           error_callback=error_callback)

    def map(self, func, iterable, chunksize=None) -> list:
        return self._map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable, chunksize=None, callback=None,
                  error_callback=None) -> AsyncResult:
        return self._map_async(func, iterable, chunksize, False, callback,
                               error_callback)

    def starmap(self, func, iterable, chunksize=None) -> list:
        return self._map_async(func, iterable, chunksize, star=True).get()

    def starmap_async(self, func, iterable, chunksize=None, callback=None,
                      error_callback=None) -> AsyncResult:
        return self._map_async(func, iterable, chunksize, True, callback,
                               error_callback)

    def imap(self, func, iterable, chunksize=1):
        self._check_running()
        refs = []
        for i, batch in enumerate(self._chunk(iterable, chunksize, False)):
            actor = self._actors[i % self._processes]
            refs.append(actor.run_batch.remote(func, batch))
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, func, iterable, chunksize=1):
        self._check_running()
        refs = []
        for i, batch in enumerate(self._chunk(iterable, chunksize, False)):
            actor = self._actors[i % self._processes]
            refs.append(actor.run_batch.remote(func, batch))
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(ready[0])

    # ----------------------------------------------------------- lifecycle
    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for a in self._actors:
            ray_tpu.kill(a)

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
