"""User-facing placement group API
(reference: python/ray/util/placement_group.py)."""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu.core import runtime as rt_mod
from ray_tpu.scheduler.placement_group import (
    VALID_STRATEGIES,
    PlacementGroup,
    PlacementGroupState,
)

__all__ = [
    "placement_group",
    "remove_placement_group",
    "get_placement_group",
    "placement_group_table",
    "PlacementGroup",
]


def _manager():
    rt = rt_mod.global_runtime
    if rt is None or rt.is_shutdown:
        from ray_tpu.core.api import init

        rt = init()
    return rt.pg_manager


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None,
                    _capture_child_tasks: bool = False) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"invalid strategy {strategy!r}; one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("placement group requires at least one bundle")
    for bundle in bundles:
        if not isinstance(bundle, dict) or not bundle:
            raise ValueError(f"invalid bundle {bundle!r}")
        if any(v < 0 for v in bundle.values()):
            raise ValueError(f"negative resource in bundle {bundle!r}")
    rt = rt_mod.global_runtime
    if rt is None or rt.is_shutdown:
        from ray_tpu.core.api import init

        rt = init()
    pg = PlacementGroup(
        id=PlacementGroupID.of(rt.job_id),
        bundles=[dict(b) for b in bundles],
        strategy=strategy,
        name=name,
        lifetime=lifetime,
        capture_child_tasks=_capture_child_tasks,
    )
    rt.pg_manager.create(pg)
    return pg


def remove_placement_group(pg: PlacementGroup) -> None:
    _manager().remove(pg.id)


def get_placement_group(name: str) -> PlacementGroup:
    pg = _manager().get_by_name(name)
    if pg is None:
        raise ValueError(f"no placement group named {name!r}")
    return pg


def placement_group_table() -> Dict[str, dict]:
    return _manager().table()
