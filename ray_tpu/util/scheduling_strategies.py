"""Scheduling strategy objects
(reference: python/ray/util/scheduling_strategies.py)."""

from ray_tpu.core.task_spec import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "NodeAffinitySchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
]
