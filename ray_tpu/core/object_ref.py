"""ObjectRef — the distributed future handed back by every remote call.

Mirrors the reference's ObjectRef (python/ray/_raylet.pyx ObjectRef class):
identity is the 28-byte ObjectID; the Python object's lifetime *is* the
local reference count (construction registers, ``__del__`` deregisters with
the owner's ReferenceCounter), which drives distributed GC exactly like the
reference's CoreWorker ref-counting hooks.
"""

from __future__ import annotations

import asyncio
import threading
import weakref
from typing import TYPE_CHECKING, Optional

from ray_tpu._private.ids import ObjectID

if TYPE_CHECKING:
    from ray_tpu.core.runtime import Runtime


class ObjectRef:
    __slots__ = ("_id", "_owner_hex", "_counter", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_hex: str = "",
                 skip_adding_local_ref: bool = False):
        self._id = object_id
        self._owner_hex = owner_hex
        # Weakref to the ReferenceCounter that registered this ref, so
        # deregistration on __del__ always hits the *owning* runtime. A
        # stale ref outliving its runtime must never decrement a counter
        # in a newer runtime (object IDs can repeat across runtimes).
        self._counter = None
        if not skip_adding_local_ref:
            rt = _maybe_runtime()
            if rt is not None:
                rt.reference_counter.add_local_ref(object_id)
                self._counter = weakref.ref(rt.reference_counter)

    # -- identity ----------------------------------------------------------
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self):
        return self._id.task_id()

    def owner_hex(self) -> str:
        return self._owner_hex

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    # -- future protocol ---------------------------------------------------
    def future(self) -> "asyncio.Future":
        """Return an asyncio.Future resolved with the object's value
        (or raising its stored error) on the running loop."""
        loop = asyncio.get_event_loop()
        fut: asyncio.Future = loop.create_future()

        def _on_ready():
            from ray_tpu.core import api

            def _set():
                if fut.cancelled():
                    return
                try:
                    fut.set_result(api.get(self, _skip_wait=True))
                except BaseException as e:  # noqa: BLE001
                    fut.set_exception(e)

            loop.call_soon_threadsafe(_set)

        _require_runtime().object_store.on_available(self._id, _on_ready)
        return fut

    def __await__(self):
        return self.future().__await__()

    # -- lifetime ----------------------------------------------------------
    def __del__(self):
        if self._counter is not None:
            try:
                rc = self._counter()
                if rc is not None:
                    rc.remove_local_ref(self._id)
            # __del__ runs at interpreter shutdown, where the logging
            # machinery itself may already be torn down; any raise here
            # prints to stderr unavoidably
            except Exception:  # raycheck: disable=RC05
                pass

    def __reduce__(self):
        # Same-process deserialization re-registers through __init__.
        # When a borrow context is active (the ref is being shipped to
        # another process, core/runtime.py process path), record the
        # borrow with the owner's ReferenceCounter NOW — the borrower
        # holds the ref for the duration the context owner decides
        # (reference: reference_count.cc borrower bookkeeping).
        ctx = getattr(_borrow_ctx, "active", None)
        if ctx is not None:
            borrower_id, collected = ctx
            rt = _maybe_runtime()
            if rt is not None and self._id not in collected:
                rt.reference_counter.add_borrower(self._id, borrower_id)
                collected.add(self._id)
        return (ObjectRef, (self._id, self._owner_hex))


_borrow_ctx = threading.local()


class borrow_context:
    """While active on this thread, every ObjectRef pickled registers
    ``borrower_id`` as a borrower with the owning runtime. The caller
    removes the borrows when the remote holder is done:

        collected: set = set()
        with borrow_context("pworker:abc", collected):
            payload = dumps(args)      # nested refs register borrows
        ... run remote work ...
        for oid in collected:
            rc.remove_borrower(oid, "pworker:abc")
    """

    def __init__(self, borrower_id: str, collected: set):
        self._entry = (borrower_id, collected)

    def __enter__(self):
        self._prev = getattr(_borrow_ctx, "active", None)
        _borrow_ctx.active = self._entry
        return self._entry[1]

    def __exit__(self, *exc):
        _borrow_ctx.active = self._prev
        return False


def _maybe_runtime() -> Optional["Runtime"]:
    from ray_tpu.core import runtime as rt_mod

    return rt_mod.global_runtime


def _require_runtime() -> "Runtime":
    rt = _maybe_runtime()
    if rt is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return rt
