"""The per-process runtime — composition root of the core.

Equivalent of the reference's CoreWorker + in-process cluster bring-up
(core_worker/core_worker.cc, python/ray/node.py): owns the object store,
reference counter, the local (or simulated multi-node) cluster of raylets,
the actor directory, and the task manager that implements retries.

In-process mode runs the *entire* cluster in one process: N raylets
(thread worker pools) sharing one zero-copy object store — the analogue of
the reference's cluster_utils.Cluster (python/ray/cluster_utils.py:101)
but cheap enough to be the default for tests and single-host work. The
multiprocess runtime (ray_tpu.cluster) swaps process-backed raylets in
behind the same interfaces.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.config import Config
from ray_tpu._private.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    TaskID,
    WorkerID,
)
from ray_tpu.core.actor_runtime import (
    ActorDirectory,
    ActorExecutor,
    ActorRecord,
    ActorState,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import MemoryStore
from ray_tpu.core.raylet import (
    ClusterState,
    DependencyManager,
    Raylet,
    _TickRateLimiter,
)
from ray_tpu.core.ref_count import ReferenceCounter
from ray_tpu.core.task_spec import (
    ActorCreationSpec,
    TaskKind,
    TaskSpec,
    scheduling_class_of,
)
from ray_tpu.exceptions import (
    ActorDiedError,
    RayActorError,
    RayTaskError,
    TaskCancelledError,
)
from ray_tpu.util import tracing as _tracing

logger = logging.getLogger(__name__)

global_runtime: Optional["Runtime"] = None
_init_lock = threading.Lock()
_job_counter = 0
_job_counter_lock = threading.Lock()

# fast-lane submit spans: at most one per runtime per this interval
# (mirrors _TickPhases.MIN_INTERVAL_S — anatomy sampling, not a log)
_SUBMIT_SPAN_MIN_INTERVAL_S = 0.01


def _next_job_id() -> JobID:
    """Process-unique job ids. time.time() seconds is NOT unique enough:
    two runtimes created within one second would share a job id, hence a
    driver task id, hence colliding put/return ObjectIDs."""
    global _job_counter
    with _job_counter_lock:
        _job_counter += 1
        return JobID.from_int(
            ((os.getpid() & 0xFFFF) << 16 | (_job_counter & 0xFFFF)))


def _lineage_cost(spec: "TaskSpec") -> int:
    """Estimated bytes a cached lineage spec pins. Dominated by inline
    bytes-like arguments (large values travel by ObjectID and cost
    nothing here); the flat overhead covers the spec object itself."""
    cost = 256
    for a in spec.args:
        if isinstance(a, (bytes, bytearray, memoryview)):
            cost += len(a)
    for v in spec.kwargs.values():
        if isinstance(v, (bytes, bytearray, memoryview)):
            cost += len(v)
    return cost


@dataclass
class WorkerContext:
    """Thread-local execution context (reference: core_worker context)."""
    task_id: TaskID = None
    actor_id: Optional[ActorID] = None
    node_id: Optional[NodeID] = None
    worker_id: Optional[WorkerID] = None
    put_counter: int = 0
    task_depth: int = 0
    assigned_resources: Dict[str, float] = field(default_factory=dict)


class Runtime:
    def __init__(
        self,
        num_cpus: Optional[float] = None,
        num_gpus: Optional[float] = None,
        resources: Optional[Dict[str, float]] = None,
        object_store_memory: Optional[int] = None,
        namespace: Optional[str] = None,
        job_id: Optional[JobID] = None,
        worker_mode: str = "thread",
        num_process_workers: Optional[int] = None,
    ):
        cfg = Config.instance()
        self.job_id = job_id or _next_job_id()
        self.namespace = namespace or f"anon_{os.urandom(4).hex()}"
        self.object_store = MemoryStore()
        from ray_tpu.scheduler.pull_manager import PullManager

        self.pull_manager = PullManager(self.object_store.capacity)
        self.object_store.pull_manager = self.pull_manager
        self.reference_counter = ReferenceCounter()
        self.reference_counter.set_eviction_callback(self._evict_object)
        self.cluster_state = ClusterState()
        self.actor_directory = ActorDirectory()
        self.kv: Dict[Tuple[str, bytes], bytes] = {}  # internal KV (gcs_kv_manager.cc)
        self._kv_lock = threading.Lock()
        self._tls = threading.local()
        self._driver_task_id = TaskID.for_driver(self.job_id)
        self._task_counter = 0
        self._lock = threading.Lock()
        # Fast-lane submit spans are SAMPLED, not per-call: a traced
        # submit storm otherwise pays span construction (name f-string,
        # context stamp, exporter fan-out) on every remote() — the
        # 13%-overhead regression of the submit micro. One sampled span
        # per interval keeps representative anatomy; unsampled submits
        # skip the span machinery entirely.
        self._submit_span_limiter = _TickRateLimiter()
        self.deps = DependencyManager(self.object_store)
        # Lineage cache: finished NORMAL task specs kept for object
        # reconstruction (reference: lineage pinning in
        # reference_count.h + TaskManager::ResubmitTask,
        # object_recovery_manager.cc). LRU-bounded.
        from collections import OrderedDict

        self._lineage: "OrderedDict[TaskID, TaskSpec]" = OrderedDict()
        self._lineage_cost: Dict[TaskID, int] = {}
        self._lineage_bytes = 0
        self._lineage_lock = threading.Lock()
        self._reconstructing: set = set()
        node_resources = dict(resources or {})
        node_resources.setdefault("CPU", num_cpus if num_cpus is not None
                                  else float(os.cpu_count() or 1))
        if num_gpus:
            node_resources["GPU"] = num_gpus
        node_resources.setdefault(
            "memory", float(cfg.object_store_memory))
        node_resources.setdefault(
            "object_store_memory", float(object_store_memory
                                         or cfg.object_store_memory))
        self.process_pool = None
        self._process_shm = None
        if worker_mode == "process":
            self._start_process_pool(num_process_workers)
        elif worker_mode != "thread":
            raise ValueError(f"unknown worker_mode {worker_mode!r}")
        self.head_raylet = self.add_node(node_resources, is_head=True)
        from ray_tpu.scheduler.placement_group import PlacementGroupManager

        self.pg_manager = PlacementGroupManager(self)
        self.cluster_state.freed_callbacks.append(self.pg_manager.retry_pending)
        self.is_shutdown = False

    def _start_process_pool(self, num_workers: Optional[int]) -> None:
        """Process execution tier (reference: worker_pool.cc forks real
        worker processes; objects move via plasma shm). Tasks execute in
        OS processes; large payloads ride the native shm store."""
        from ray_tpu.cluster.process_pool import ProcessWorkerPool

        shm_path = ""
        try:
            from ray_tpu._native.shm_store import ShmStore, native_available

            if native_available():
                self._process_shm = ShmStore()
                shm_path = self._process_shm.path
        except Exception:
            logger.info("native shm store unavailable; process workers "
                        "will use inline pipe transport")
        size = num_workers or min(8, os.cpu_count() or 4)
        self.process_pool = ProcessWorkerPool(size, shm_path)

    # ----------------------------------------------------------- node mgmt
    def add_node(self, resources: Dict[str, float], is_head: bool = False,
                 labels: Optional[Dict[str, str]] = None) -> Raylet:
        node_id = NodeID.from_random()
        raylet = Raylet(node_id, resources, self.cluster_state, self.deps,
                        labels=labels)
        self.cluster_state.register(raylet)
        for r in self.cluster_state.raylets.values():
            r.retry_infeasible()
        # new capacity may unblock pending placement groups
        self.cluster_state.notify_freed()
        return raylet

    def drain_node(self, node_id: NodeID,
                   deadline_s: Optional[float] = None) -> None:
        """Graceful in-process node removal (drain plane): the node
        leaves every placement solve immediately (ClusterState.
        set_draining flips its matrix alive-mask row), queued and
        running work gets the drain deadline to finish or spill, and
        whatever is left falls to remove_node's recovery path — a
        wedged drain degrades to the hard-removal semantics instead of
        stranding work. With the plane off this IS remove_node."""
        cfg = Config.instance()
        raylet = self.cluster_state.raylets.get(node_id)
        if raylet is None:
            return
        if not cfg.drain_plane_enabled:
            self.remove_node(node_id)
            return
        self.cluster_state.set_draining(node_id)
        raylet.drain(cfg.drain_deadline_s if deadline_s is None
                     else deadline_s)
        self.remove_node(node_id)

    def remove_node(self, node_id: NodeID) -> None:
        raylet = self.cluster_state.raylets.get(node_id)
        if raylet is None:
            return
        self.cluster_state.unregister(node_id)
        lost = raylet.extract_outstanding()
        raylet.shutdown()
        # Resubmit tasks the dead node never ran (reference: raylet death
        # fails outstanding leases; the owning CoreWorker retries).
        for task in lost:
            self.resubmit_lost_task(task.spec)
        # Fail actors that lived on this node; restart if budget remains.
        for rec in self.actor_directory.list():
            if rec.node_id == node_id and rec.state is ActorState.ALIVE:
                self._handle_actor_node_death(rec)
        pg_manager = getattr(self, "pg_manager", None)
        if pg_manager is not None:
            pg_manager.handle_node_death(node_id)

    # ------------------------------------------------------------- context
    def context(self) -> WorkerContext:
        ctx = getattr(self._tls, "ctx", None)
        if ctx is None:
            # Threads the executor did not set up (user-spawned threads,
            # e.g. train-session threads) must NOT share the driver's
            # task id: each thread's put_counter starts at 0, so two
            # such threads would mint identical ObjectID.for_put ids
            # and silently overwrite each other's puts (the r05
            # allreduce corruption). The driver's main thread keeps the
            # stable driver task id; every other unknown thread gets a
            # fresh unique one.
            import threading as _threading

            if _threading.current_thread() is _threading.main_thread():
                tid = self._driver_task_id
            else:
                tid = TaskID.for_task(None)
            ctx = WorkerContext(task_id=tid,
                                node_id=self.head_raylet.node_id)
            self._tls.ctx = ctx
        return ctx

    def _next_task_id(self, actor_id: Optional[ActorID] = None) -> TaskID:
        return TaskID.for_task(actor_id)

    # ------------------------------------------------------------- put/get
    def put(self, value: Any) -> ObjectRef:
        ctx = self.context()
        ctx.put_counter += 1
        oid = ObjectID.for_put(ctx.task_id, ctx.put_counter)
        self.reference_counter.add_owned_object(oid)
        self.object_store.put(oid, value)
        return ObjectRef(oid)

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None
            ) -> List[Any]:
        from ray_tpu.exceptions import ObjectCorruptedError

        if Config.instance().enable_object_reconstruction:
            for r in refs:
                if not self.object_store.contains(r.id()):
                    self.maybe_reconstruct(r.id())
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                stored = self.object_store.get(
                    [r.id() for r in refs], remaining)
                break
            except ObjectCorruptedError as e:
                # a spilled copy failed its digest and discarded
                # itself (integrity plane): recompute it via lineage
                # and retry the get — the caller sees the correct
                # value or this typed error, never garbage
                if not Config.instance().enable_object_reconstruction:
                    raise
                recovered = False
                for r in refs:
                    if (r.id().hex() == e.object_id_hex
                            and not self.object_store.contains(r.id())):
                        recovered = (self.maybe_reconstruct(r.id())
                                     or recovered)
                if not recovered:
                    raise
        out = []
        for obj in stored:
            if obj.is_error:
                err = obj.value
                if isinstance(err, RayTaskError):
                    raise err.as_instanceof_cause()
                raise err
            out.append(obj.value)
        return out

    def wait(self, refs: Sequence[ObjectRef], num_returns: int,
             timeout: Optional[float]) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        by_id = {r.id(): r for r in refs}
        ready, unready = self.object_store.wait(
            [r.id() for r in refs], num_returns, timeout)
        return [by_id[o] for o in ready], [by_id[o] for o in unready]

    def _evict_object(self, object_id: ObjectID) -> None:
        self.object_store.delete(object_id)

    # -------------------------------------------------------- task submit
    def submit_task(self, func, func_name: str, args: tuple, kwargs: dict,
                    options, template=None) -> List[ObjectRef]:
        if template is not None \
                and Config.instance().dispatch_fastlane_enabled:
            return self._submit_task_fast(func, func_name, args, kwargs,
                                          template)
        ctx = self.context()
        task_id = self._next_task_id()
        resources = options.resolved_resources()
        num_returns = options.num_returns
        return_ids = tuple(
            ObjectID.for_return(task_id, i + 1) for i in range(num_returns))
        strategy = self._resolve_strategy(options, ctx)
        spec = TaskSpec(
            kind=TaskKind.NORMAL,
            task_id=task_id,
            job_id=self.job_id,
            parent_task_id=ctx.task_id,
            name=options.name or func_name,
            func=func,
            func_descriptor=func_name,
            args=args,
            kwargs=kwargs,
            num_returns=num_returns,
            return_ids=return_ids,
            resources=resources,
            scheduling_strategy=strategy,
            max_retries=options.max_retries,
            retries_left=max(0, options.max_retries),
            retry_exceptions=options.retry_exceptions,
            depth=ctx.task_depth + 1,
            runtime_env=_normalize_runtime_env(options.runtime_env),
            submit_time=time.monotonic(),
        )
        # PG options rewrite spec.resources; the class must intern the
        # FINAL demand or same-class tasks would carry different demands
        # (the batch solve and per-class dispatch queues rely on
        # class => one demand).
        self._apply_placement_options(spec, options, ctx)
        spec.scheduling_class = scheduling_class_of(
            spec.resource_request(self.cluster_state.ids), func_name)
        for oid in return_ids:
            self.reference_counter.add_owned_object(oid, creating_task=task_id)
        self._track_arg_refs(spec, add=True)
        refs = [ObjectRef(oid) for oid in return_ids]
        from ray_tpu.util import tracing

        def _stamp(span):
            spec.trace_context = span.context().to_dict()

        with tracing.maybe_span(
                lambda: f"task::{spec.name}.remote",
                attributes_fn=lambda: {"task_id": task_id.hex()},
                on_span=_stamp):
            self._submit_to_raylet(spec)
        return refs

    def _submit_task_fast(self, func, func_name: str, args: tuple,
                          kwargs: dict, template) -> List[ObjectRef]:
        """Fast-lane submit for templated plain tasks (dispatch fast
        lane). The :class:`~ray_tpu.core.task_spec.TaskTemplate` froze
        the resolved resources, retry policy, strategy, and — per
        id-map — the SHARED ResourceRequest and interned scheduling
        class at decoration time, so each call only mints IDs and
        stamps the spec; the per-call ``resolved_resources()`` dict
        build, ``from_map`` id-lock walk, and ``scheduling_class_of``
        global-lock intern all disappear. Placement groups and runtime
        envs never reach here (template eligibility excludes them);
        refcounting, backpressure, and trace propagation follow the
        general path exactly."""
        ctx = self.context()
        task_id = self._next_task_id()
        num_returns = template.num_returns
        if num_returns == 1:  # the overwhelmingly common case: no genexpr
            return_ids = (ObjectID.for_return(task_id, 1),)
        else:
            return_ids = tuple(
                ObjectID.for_return(task_id, i + 1)
                for i in range(num_returns))
        req, scheduling_class = template.demand(self.cluster_state.ids)
        spec = TaskSpec(
            kind=TaskKind.NORMAL,
            task_id=task_id,
            job_id=self.job_id,
            parent_task_id=ctx.task_id,
            name=template.name,
            func=func,
            func_descriptor=func_name,
            args=args,
            kwargs=kwargs,
            num_returns=num_returns,
            return_ids=return_ids,
            # the template's resource map and request are shared across
            # specs: nothing on the plain-task path mutates either (PG
            # rewrites — the one mutator — are template-ineligible)
            resources=template.resources,
            scheduling_class=scheduling_class,
            scheduling_strategy=template.scheduling_strategy,
            max_retries=template.max_retries,
            retries_left=template.retries_left,
            retry_exceptions=template.retry_exceptions,
            depth=ctx.task_depth + 1,
            submit_time=time.monotonic(),
            _req_cache=req,
        )
        add_owned = self.reference_counter.add_owned_object
        for oid in return_ids:
            add_owned(oid, creating_task=task_id)
        if args or kwargs:
            self._track_arg_refs(spec, add=True)
        refs = [ObjectRef(oid) for oid in return_ids]
        if not _tracing.enabled() or not self._submit_span_limiter \
                .try_acquire(time.monotonic(),
                             _SUBMIT_SPAN_MIN_INTERVAL_S):
            # span thunks + the contextmanager frame are measurable at
            # this call rate; spans are sampled to one per interval —
            # a traced submit storm takes this branch for every call
            # between samples (clock read + lock-free compare)
            self._submit_to_raylet(spec)
            return refs

        def _stamp(span):
            spec.trace_context = span.context().to_dict()

        with _tracing.maybe_span(
                lambda: f"task::{spec.name}.remote",
                attributes_fn=lambda: {"task_id": task_id.hex()},
                on_span=_stamp):
            self._submit_to_raylet(spec)
        return refs

    def _resolve_strategy(self, options, ctx) -> Any:
        strategy = options.scheduling_strategy
        if strategy in (None, "DEFAULT"):
            return None
        return strategy

    def _apply_placement_options(self, spec: TaskSpec, options, ctx) -> None:
        pg = getattr(options, "placement_group", None)
        strategy = options.scheduling_strategy
        from ray_tpu.core.task_spec import PlacementGroupSchedulingStrategy

        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            pg = strategy.placement_group
            spec.placement_group_bundle_index = (
                strategy.placement_group_bundle_index)
            spec.capture_child_tasks = bool(
                strategy.placement_group_capture_child_tasks)
        elif pg is not None:
            spec.placement_group_bundle_index = (
                options.placement_group_bundle_index)
        if pg is not None:
            spec.placement_group_id = pg.id
            # Rewrite the demand onto the PG's shadow resources
            # (reference: placement_group_resource_manager.cc formats
            # CPU_group_<index>_<pgid> / CPU_group_<pgid>).
            from ray_tpu.scheduler.placement_group import rewrite_resources_for_pg

            spec.resources = rewrite_resources_for_pg(
                spec.resources, pg, spec.placement_group_bundle_index)
            spec._req_cache = None  # demand changed: drop memoized request

    def _track_arg_refs(self, spec: TaskSpec, add: bool) -> None:
        for a in list(spec.args) + list(spec.kwargs.values()):
            if isinstance(a, ObjectRef):
                if add:
                    self.reference_counter.add_submitted_task_ref(a.id())
                else:
                    self.reference_counter.remove_submitted_task_ref(a.id())

    def _submit_to_raylet(self, spec: TaskSpec) -> None:
        ctx = self.context()
        raylet = self.cluster_state.raylets.get(ctx.node_id,
                                                self.head_raylet)
        self._submit_with_backpressure(raylet, spec)

    def _submit_with_backpressure(self, raylet: Raylet,
                                  spec: TaskSpec) -> None:
        """Backpressure: a raylet whose submit queue is at its bound
        raises RetryLaterError — this loop slows the producer down at
        the hinted pace (instead of queuing unboundedly) and retries
        until the backlog drains or the backpressure window lapses."""
        from ray_tpu._private.config import Config
        from ray_tpu.exceptions import RetryLaterError

        deadline = (time.monotonic()
                    + Config.instance().submit_backpressure_timeout_s)
        while True:
            try:
                raylet.submit(spec, self._make_dispatch(spec))
                return
            except RetryLaterError as e:
                if time.monotonic() + e.retry_after_s >= deadline:
                    raise
                time.sleep(e.retry_after_s)

    # ------------------------------------------------------- task execution
    def _make_dispatch(self, spec: TaskSpec):
        def _dispatch(raylet: Raylet, worker_id: WorkerID):
            self._execute_spec(spec, raylet, worker_id)
        return _dispatch

    def _execute_spec(self, spec: TaskSpec, raylet: Raylet,
                      worker_id: WorkerID) -> None:
        """Runs on a worker thread of the chosen raylet
        (reference: CoreWorker::ExecuteTask, core_worker.cc:2069)."""
        ctx = WorkerContext(
            task_id=spec.task_id,
            actor_id=spec.actor_id,
            node_id=raylet.node_id,
            worker_id=worker_id,
            task_depth=spec.depth,
            assigned_resources=dict(spec.resources),
        )
        self._tls.ctx = ctx
        from ray_tpu.util import tracing

        trace_parent = tracing.SpanContext.from_dict(spec.trace_context)
        try:
            with tracing.start_span(
                    f"task::{spec.name}.execute", parent=trace_parent,
                    attributes={"task_id": spec.task_id.hex(),
                                "node_id": raylet.node_id.hex(),
                                "worker_id": worker_id.hex()}):
                self._execute_spec_inner(spec, raylet)
            self.record_lineage(spec)
        except TaskCancelledError as e:
            self._store_error(spec, e)
        except BaseException as e:  # noqa: BLE001
            self._handle_task_error(spec, e, raylet)
        finally:
            self._track_arg_refs(spec, add=False)
            self._tls.ctx = None

    def _execute_spec_inner(self, spec: TaskSpec, raylet: Raylet) -> None:
        if spec.runtime_env is not None:
            # URI refcount for the env's lifetime (reference: runtime-env
            # agent URI reference counting)
            spec.runtime_env.acquire()
            try:
                self._execute_spec_body(spec, raylet)
            finally:
                spec.runtime_env.release()
            return
        self._execute_spec_body(spec, raylet)

    def _execute_spec_body(self, spec: TaskSpec, raylet: Raylet) -> None:
        args = self._resolve_args(spec.args)
        kwargs = {k: self._resolve_arg(v) for k, v in spec.kwargs.items()}
        if (self.process_pool is not None
                and spec.kind is TaskKind.NORMAL):
            # Refs nested inside args ship to the worker process as refs:
            # the worker is a genuine borrower for the task's lifetime
            # (reference: reference_count.cc borrower protocol; borrows
            # clear when the task finishes, like WaitForRefRemoved).
            from ray_tpu.core.object_ref import borrow_context

            borrower_id = f"pworker:{spec.task_id.hex()}"
            borrowed: set = set()
            try:
                with borrow_context(borrower_id, borrowed):
                    result = self.process_pool.run(
                        spec.func, tuple(args), kwargs,
                        runtime_env=spec.runtime_env)
            finally:
                for oid in borrowed:
                    self.reference_counter.remove_borrower(
                        oid, borrower_id)
        elif (self.process_pool is not None
                and spec.kind is TaskKind.ACTOR_CREATION):
            # env is applied inside the dedicated worker process for
            # the actor's whole life; applying it parent-side too
            # would mutate the driver's environ for no benefit
            result = spec.func(*args, **kwargs)
        elif spec.runtime_env is not None:
            with spec.runtime_env.applied():
                result = spec.func(*args, **kwargs)
        else:
            result = spec.func(*args, **kwargs)
        self._store_results(spec, result)

    def _resolve_args(self, args: tuple) -> list:
        return [self._resolve_arg(a) for a in args]

    def _resolve_arg(self, arg: Any) -> Any:
        if isinstance(arg, ObjectRef):
            stored = self.object_store.peek(arg.id())
            if stored is None:
                # dependency manager guaranteed availability; a miss means
                # the object was lost after scheduling
                stored_list = self.object_store.get([arg.id()], timeout=1.0)
                stored = stored_list[0]
            if stored.is_error:
                err = stored.value
                if isinstance(err, RayTaskError):
                    raise err.as_instanceof_cause()
                raise err
            return stored.value
        return arg

    def _store_results(self, spec: TaskSpec, result: Any) -> None:
        if spec.num_returns == 0:
            return
        if spec.num_returns == 1:
            self.object_store.put(spec.return_ids[0], result)
            return
        values = list(result) if result is not None else []
        if len(values) != spec.num_returns:
            err = RayTaskError(
                spec.name,
                f"task declared num_returns={spec.num_returns} but returned "
                f"{len(values)} values", None)
            for oid in spec.return_ids:
                self.object_store.put(oid, err, is_error=True)
            return
        for oid, v in zip(spec.return_ids, values):
            self.object_store.put(oid, v)

    def _handle_task_error(self, spec: TaskSpec, exc: BaseException,
                           raylet: Raylet) -> None:
        retryable = self._is_retryable(spec, exc)
        if retryable and spec.retries_left > 0:
            spec.retries_left -= 1
            logger.info("retrying task %s (%d retries left)",
                        spec.name, spec.retries_left)
            delay = Config.instance().task_retry_delay_ms / 1000.0
            if delay:
                time.sleep(delay)
            self._submit_with_backpressure(raylet, spec)
            return
        self._store_error(
            spec,
            exc if isinstance(exc, RayTaskError) else RayTaskError.from_exception(
                spec.name, exc, pid=os.getpid(),
                node_hex=raylet.node_id.hex()))

    def _is_retryable(self, spec: TaskSpec, exc: BaseException) -> bool:
        retry_exceptions = spec.retry_exceptions
        if retry_exceptions is True:
            return True
        if isinstance(retry_exceptions, (list, tuple)):
            return isinstance(exc, tuple(retry_exceptions))
        # Default: retry only system errors (worker crash), which cannot
        # occur for thread workers; process workers raise WorkerCrashedError.
        from ray_tpu.exceptions import WorkerCrashedError

        return isinstance(exc, WorkerCrashedError)

    def _store_error(self, spec: TaskSpec, err: BaseException) -> None:
        if not isinstance(err, RayTaskError) and not isinstance(
                err, (RayActorError, TaskCancelledError)):
            err = RayTaskError.from_exception(spec.name, err)
        for oid in spec.return_ids:
            self.object_store.put(oid, err, is_error=True)

    def store_task_cancelled(self, spec: TaskSpec) -> None:
        self._store_error(spec, TaskCancelledError(spec.task_id))
        self._track_arg_refs(spec, add=False)

    # ---------------------------------------------------------------- actors
    def create_actor(self, cls, cls_name: str, init_args: tuple,
                     init_kwargs: dict, options) -> "ActorRecord":
        import inspect as _inspect

        actor_id = ActorID.of(self.job_id)
        is_async = any(
            _inspect.iscoroutinefunction(m)
            for _, m in _inspect.getmembers(cls, _inspect.isfunction))
        creation = ActorCreationSpec(
            actor_id=actor_id, cls=cls, cls_descriptor=cls_name,
            init_args=init_args, init_kwargs=init_kwargs, options=options,
            is_async=is_async, max_restarts=options.max_restarts)
        record = ActorRecord(
            actor_id=actor_id,
            state=ActorState.PENDING_CREATION,
            creation_spec=creation,
            name=options.name,
            namespace=options.namespace or self.namespace,
            detached=(options.lifetime == "detached"),
            restarts_remaining=(
                -1 if options.max_restarts == -1 else options.max_restarts),
        )
        self.actor_directory.register(record)
        self._submit_actor_creation(record)
        return record

    def _submit_actor_creation(self, record: ActorRecord) -> None:
        creation: ActorCreationSpec = record.creation_spec
        options = creation.options
        ctx = self.context()
        task_id = self._next_task_id(creation.actor_id)
        spec = TaskSpec(
            kind=TaskKind.ACTOR_CREATION,
            task_id=task_id,
            job_id=self.job_id,
            parent_task_id=ctx.task_id,
            name=f"{creation.cls_descriptor}.__init__",
            func=None,
            args=creation.init_args,
            kwargs=creation.init_kwargs,
            num_returns=1,
            return_ids=(ObjectID.for_return(task_id, 1),),
            resources=options.placement_resources(),
            scheduling_strategy=options.scheduling_strategy,
            actor_id=creation.actor_id,
            max_retries=0,
            # in-process workers share one interpreter, so the env applies
            # around __init__ (the reference holds it for the process life)
            runtime_env=_normalize_runtime_env(options.runtime_env),
            submit_time=time.monotonic(),
        )
        self._apply_placement_options(spec, options, ctx)
        spec.scheduling_class = scheduling_class_of(
            spec.resource_request(self.cluster_state.ids),
            creation.cls_descriptor)
        self.reference_counter.add_owned_object(spec.return_ids[0],
                                                creating_task=task_id)
        spec.func = lambda *a, **kw: self._instantiate_actor(record, a, kw)
        self._track_arg_refs(spec, add=True)
        self._submit_to_raylet(spec)

    def _instantiate_actor(self, record: ActorRecord, args, kwargs):
        creation: ActorCreationSpec = record.creation_spec
        options = creation.options
        ctx = self.context()
        if record.state is ActorState.DEAD:
            # killed while still pending creation; don't resurrect
            # (reference: gcs_actor_manager.cc DestroyActor on pending)
            raise ActorDiedError("actor was killed before creation finished")
        try:
            if self.process_pool is not None:
                # dedicated worker process per actor (reference: every
                # actor gets its own worker; direct_actor_transport)
                instance = self.process_pool.create_actor_process(
                    creation.cls, args, kwargs,
                    runtime_env=_normalize_runtime_env(options.runtime_env))
            else:
                instance = creation.cls(*args, **kwargs)
        except BaseException:
            self.actor_directory.mark_dead(
                record.actor_id, cause="creation task failed")
            self._fail_buffered_calls(record)
            raise
        max_concurrency = options.max_concurrency or (
            1000 if creation.is_async else 1)
        record.executor = ActorExecutor(
            record.actor_id, instance, max_concurrency, creation.is_async,
            options.concurrency_groups,
            execute_out_of_order=options.execute_out_of_order)
        record.node_id = ctx.node_id
        # Downgrade from placement to lifetime resources (reference:
        # actors hold 0 CPU while alive unless explicitly requested).
        raylet = self.cluster_state.raylets.get(ctx.node_id)
        lifetime = options.lifetime_resources()
        if raylet is not None and lifetime:
            raylet.adjust_resources(lifetime, allocate=True)
        with record.lock:
            if record.state is ActorState.DEAD:  # killed mid-__init__
                executor = record.executor
                record.executor = None
            else:
                record.state = ActorState.ALIVE
                executor = None
        if executor is not None:
            executor.kill()
            if raylet is not None and lifetime:
                raylet.adjust_resources(lifetime, allocate=False)
            raise ActorDiedError("actor was killed during creation")
        self.actor_directory.flush_buffered(record.actor_id)
        return record.actor_id

    def submit_actor_task(self, record: ActorRecord, method_name: str,
                          args: tuple, kwargs: dict, num_returns: int,
                          concurrency_group: str = "") -> List[ObjectRef]:
        if record.state is ActorState.DEAD:
            oid = ObjectID.for_return(self._next_task_id(record.actor_id), 1)
            self.reference_counter.add_owned_object(oid)
            self.object_store.put(
                oid, ActorDiedError(
                    f"Actor {record.actor_id.hex()[:8]} is dead: "
                    f"{record.death_cause}"), is_error=True)
            return [ObjectRef(oid)]
        opts = record.creation_spec.options
        if opts.max_pending_calls > 0 and record.executor is not None:
            from ray_tpu.exceptions import PendingCallsLimitExceeded

            if record.executor.pending_count() >= opts.max_pending_calls:
                raise PendingCallsLimitExceeded(
                    f"max_pending_calls={opts.max_pending_calls} exceeded")
        task_id = self._next_task_id(record.actor_id)
        return_ids = tuple(
            ObjectID.for_return(task_id, i + 1) for i in range(num_returns))
        for oid in return_ids:
            self.reference_counter.add_owned_object(oid, creating_task=task_id)
        names = record.creation_spec.__dict__.setdefault(
            "_method_name_cache", {})
        full_name = names.get(method_name)
        if full_name is None:
            full_name = f"{record.creation_spec.cls_descriptor}.{method_name}"
            names[method_name] = full_name
        spec = TaskSpec(
            kind=TaskKind.ACTOR_TASK,
            task_id=task_id,
            job_id=self.job_id,
            parent_task_id=self.context().task_id,
            name=full_name,
            args=args,
            kwargs=kwargs,
            num_returns=num_returns,
            return_ids=return_ids,
            actor_id=record.actor_id,
            max_retries=record.creation_spec.options.max_task_retries,
            retries_left=max(0, record.creation_spec.options.max_task_retries),
            submit_time=time.monotonic(),
        )
        self._track_arg_refs(spec, add=True)
        refs = [ObjectRef(oid) for oid in return_ids]
        from ray_tpu.util import tracing

        def _submit():
            self._enqueue_actor_task(record, spec, method_name,
                                     concurrency_group)

        def _route():
            if record.state is ActorState.ALIVE and \
                    record.executor is not None:
                _submit()
            else:
                with record.lock:
                    record.buffered_calls.append(_submit)
                # race: ALIVE may have flipped while appending
                if record.state is ActorState.ALIVE:
                    self.actor_directory.flush_buffered(record.actor_id)
                elif record.state is ActorState.DEAD:
                    self._fail_buffered_calls(record)

        def _stamp(span):
            spec.trace_context = span.context().to_dict()

        with tracing.maybe_span(
                lambda: f"actor_task::{spec.name}.remote",
                attributes_fn=lambda: {
                    "task_id": task_id.hex(),
                    "actor_id": record.actor_id.hex()},
                on_span=_stamp):
            _route()
        return refs

    def _enqueue_actor_task(self, record: ActorRecord, spec: TaskSpec,
                            method_name: str, concurrency_group: str) -> None:
        executor = record.executor
        if executor is None or record.state is ActorState.DEAD:
            self._store_error(spec, ActorDiedError())
            self._track_arg_refs(spec, add=False)
            return

        def _execute():
            ctx = WorkerContext(
                task_id=spec.task_id, actor_id=record.actor_id,
                node_id=record.node_id, task_depth=spec.depth)
            self._tls.ctx = ctx
            try:
                # Args resolve on the actor's executor slot so a failed
                # dependency still consumes this sequence number (a skipped
                # seq would deadlock the strict-order queue).
                from ray_tpu.util import tracing

                with tracing.maybe_span(
                        lambda: f"actor_task::{spec.name}.execute",
                        parent=tracing.SpanContext.from_dict(
                            spec.trace_context),
                        attributes_fn=lambda: {
                            "task_id": spec.task_id.hex(),
                            "actor_id": record.actor_id.hex()}):
                    args = self._resolve_args(spec.args)
                    kwargs = {k: self._resolve_arg(v)
                              for k, v in spec.kwargs.items()}
                    method = getattr(executor.instance, method_name)
                    result = method(*args, **kwargs)
                if executor.is_async and hasattr(result, "__await__"):
                    async def _await_and_store():
                        try:
                            value = await result
                            self._store_results(spec, value)
                        except BaseException as e:  # noqa: BLE001
                            self._actor_task_error(record, spec, e)
                        finally:
                            self._track_arg_refs(spec, add=False)

                    return _await_and_store()
                self._store_results(spec, result)
                self._track_arg_refs(spec, add=False)
            except BaseException as e:  # noqa: BLE001
                self._actor_task_error(record, spec, e)
                self._track_arg_refs(spec, add=False)
            finally:
                self._tls.ctx = None

        def _fail():
            # Actor died with this call still queued. Retry across the
            # restart if the task has budget (reference: max_task_retries,
            # direct_actor_task_submitter.cc resubmit on restart).
            if spec.retries_left > 0 and record.restarts_remaining != 0 \
                    and record.state is not ActorState.DEAD:
                spec.retries_left -= 1
                with record.lock:
                    record.buffered_calls.append(
                        lambda: self._enqueue_actor_task(
                            record, spec, method_name, concurrency_group))
                if record.state is ActorState.ALIVE:
                    self.actor_directory.flush_buffered(record.actor_id)
                return
            self._store_error(spec, ActorDiedError())
            self._track_arg_refs(spec, add=False)

        # Sequence numbers are assigned at enqueue time, per executor
        # incarnation, so execution follows submission order even across
        # dependency waits; buffered calls renumber after a restart (the
        # reference resets sequence state on reconnect). A call whose
        # dependency fails still consumes its number inside _execute.
        spec.sequence_number = record.next_seq()

        def _when_deps_ready():
            executor.submit(spec.sequence_number, method_name, _execute,
                            fail=_fail, concurrency_group=concurrency_group)

        self.deps.wait_ready(spec, _when_deps_ready)

    def _actor_task_error(self, record: ActorRecord, spec: TaskSpec,
                          exc: BaseException) -> None:
        from ray_tpu.exceptions import AsyncioActorExit

        if isinstance(exc, (AsyncioActorExit, SystemExit)):
            # exit_actor() path
            self._store_results(spec, None)
            self.kill_actor(record, no_restart=True, graceful=True)
            return
        from ray_tpu.exceptions import WorkerCrashedError

        if isinstance(exc, WorkerCrashedError):
            # The actor's worker process died under this call (reference:
            # worker disconnect → GCS ReconstructActor, in-flight calls
            # fail or retry across the restart per max_task_retries).
            self._handle_actor_worker_death(record, cause=str(exc))
            if spec.retries_left > 0 and record.state is not ActorState.DEAD:
                spec.retries_left -= 1
                method_name = spec.name.rsplit(".", 1)[-1]
                # compensate for the caller's unconditional ref release
                self._track_arg_refs(spec, add=True)
                with record.lock:
                    record.buffered_calls.append(
                        lambda: self._enqueue_actor_task(
                            record, spec, method_name, ""))
                if record.state is ActorState.ALIVE:
                    self.actor_directory.flush_buffered(record.actor_id)
                elif record.state is ActorState.DEAD:
                    self._fail_buffered_calls(record)
                return
            self._store_error(spec, ActorDiedError(
                f"actor worker process died: {exc}"))
            return
        if self._is_retryable(spec, exc) and spec.retries_left > 0:
            spec.retries_left -= 1
            method_name = spec.name.rsplit(".", 1)[-1]
            # compensate for the caller's unconditional ref release
            self._track_arg_refs(spec, add=True)
            self._enqueue_actor_task(record, spec, method_name, "")
            return
        self._store_error(spec, RayTaskError.from_exception(
            spec.name, exc, pid=os.getpid(),
            node_hex=record.node_id.hex() if record.node_id else ""))

    def _fail_buffered_calls(self, record: ActorRecord) -> None:
        with record.lock:
            calls, record.buffered_calls = record.buffered_calls, []
        # buffered closures would enqueue; instead mark dead so each call
        # stores an ActorDiedError
        for call in calls:
            call()

    def kill_actor(self, record: ActorRecord, no_restart: bool = True,
                   graceful: bool = False) -> None:
        with record.lock:
            if record.state is ActorState.DEAD:
                return
            was_alive = record.state is ActorState.ALIVE
            executor = record.executor
        raylet = (self.cluster_state.raylets.get(record.node_id)
                  if record.node_id else None)
        lifetime = record.creation_spec.options.lifetime_resources()
        if not no_restart and record.restarts_remaining != 0:
            if executor is not None:
                executor.kill()
                if raylet is not None and lifetime and was_alive:
                    raylet.adjust_resources(lifetime, allocate=False)
            self._restart_actor(record, "killed with restart budget")
            return
        self.actor_directory.mark_dead(
            record.actor_id,
            cause="ray_tpu.kill" if not graceful else "actor exited")
        if executor is not None:
            executor.kill()
            if raylet is not None and lifetime and was_alive:
                raylet.adjust_resources(lifetime, allocate=False)
        self._fail_buffered_calls(record)

    def _handle_actor_worker_death(self, record: ActorRecord,
                                   cause: str) -> None:
        """The actor's dedicated worker process crashed (process mode)."""
        with record.lock:
            if record.state is not ActorState.ALIVE:
                # another thread already handled this crash (concurrent
                # in-flight calls all observe WorkerCrashedError)
                return
            record.state = ActorState.RESTARTING
            executor = record.executor
            record.executor = None
        raylet = (self.cluster_state.raylets.get(record.node_id)
                  if record.node_id else None)
        lifetime = record.creation_spec.options.lifetime_resources()
        if executor is not None:
            executor.kill()
            if raylet is not None and lifetime:
                raylet.adjust_resources(lifetime, allocate=False)
        if record.restarts_remaining != 0:
            self._restart_actor(record, cause)
        else:
            self.actor_directory.mark_dead(record.actor_id, cause=cause)
            self._fail_buffered_calls(record)

    def _handle_actor_node_death(self, record: ActorRecord) -> None:
        executor = record.executor
        if executor is not None:
            executor.kill()
        if record.restarts_remaining != 0:
            self._restart_actor(record, "node died")
        else:
            self.actor_directory.mark_dead(record.actor_id, cause="node died")
            self._fail_buffered_calls(record)

    def _restart_actor(self, record: ActorRecord, cause: str) -> None:
        """ReconstructActor (reference: gcs_actor_manager.cc:945)."""
        if record.restarts_remaining > 0:
            record.restarts_remaining -= 1
        record.num_restarts += 1
        with record.lock:
            record.state = ActorState.RESTARTING
            old_executor = record.executor
            record.executor = None
            record.seq_counter = 0
        if old_executor is not None and not old_executor.dead:
            old_executor.kill()
        self._submit_actor_creation(record)

    # ------------------------------------------------- lineage reconstruction
    def record_lineage(self, spec: TaskSpec) -> None:
        """Cache a finished task's spec so its outputs can be recomputed
        if lost (reference: lineage pinning, reference_count.h). LRU,
        bounded both by entry count (``max_lineage_entries``) and by an
        estimated byte budget (``max_lineage_bytes`` — the reference's
        RAY_max_lineage_bytes cap): a few huge inline-arg specs must
        not pin gigabytes just because they are few."""
        if spec.kind is not TaskKind.NORMAL or spec.func is None:
            return
        cfg = Config.instance()
        max_entries = cfg.max_lineage_entries
        max_bytes = cfg.max_lineage_bytes
        cost = _lineage_cost(spec)
        with self._lineage_lock:
            if spec.task_id in self._lineage:
                self._lineage_bytes -= self._lineage_cost.pop(
                    spec.task_id, 0)
            self._lineage[spec.task_id] = spec
            self._lineage_cost[spec.task_id] = cost
            self._lineage_bytes += cost
            self._lineage.move_to_end(spec.task_id)
            while self._lineage and (
                    len(self._lineage) > max_entries
                    or self._lineage_bytes > max_bytes):
                evicted_id, _ = self._lineage.popitem(last=False)
                self._lineage_bytes -= self._lineage_cost.pop(
                    evicted_id, 0)

    def maybe_reconstruct(self, object_id: ObjectID, _depth: int = 0
                          ) -> bool:
        """Re-execute the creating task of a lost object, recursively
        recovering lost arguments first (reference:
        ObjectRecoveryManager::RecoverObject -> lineage re-execution).
        Returns True if a reconstruction was submitted or is in flight."""
        if _depth > 100:
            return False
        task_id = object_id.task_id()
        with self._lineage_lock:
            spec = self._lineage.get(task_id)
            if spec is None:
                return False
            if task_id in self._reconstructing:
                return True  # a concurrent get already resubmitted it
            self._reconstructing.add(task_id)
        # recover lost arguments first; the dependency manager then waits
        # for them like any other pending args
        for arg in list(spec.args) + list(spec.kwargs.values()):
            if isinstance(arg, ObjectRef) and \
                    not self.object_store.contains(arg.id()):
                self.maybe_reconstruct(arg.id(), _depth + 1)
        logger.info("reconstructing object %s via task %s",
                    object_id.hex()[:8], spec.name)

        def _clear():
            with self._lineage_lock:
                self._reconstructing.discard(task_id)

        for oid in spec.return_ids:
            self.object_store.on_available(oid, _clear)
        self._track_arg_refs(spec, add=True)
        self._submit_to_raylet(spec)
        return True

    def resubmit_lost_task(self, spec: TaskSpec) -> None:
        """A placed-but-unfinished task's node died. Actor creations
        re-place unconditionally (restart budget is actor-level); normal
        tasks consume a retry as a system failure (reference:
        TaskManager::RetryTaskIfPossible, task_manager.cc:347)."""
        from ray_tpu.exceptions import WorkerCrashedError

        if self.is_shutdown:
            return
        if spec.kind is TaskKind.ACTOR_CREATION:
            self._submit_to_raylet(spec)
            return
        if spec.max_retries == -1 or spec.retries_left > 0:
            if spec.max_retries != -1:
                spec.retries_left -= 1
            logger.info("resubmitting task %s lost to node death "
                        "(%d retries left)", spec.name, spec.retries_left)
            self._submit_to_raylet(spec)
            return
        self._store_error(spec, WorkerCrashedError(
            f"task {spec.name} lost to node death and out of retries"))
        self._track_arg_refs(spec, add=False)

    # ---------------------------------------------------------------- misc
    def cancel_task(self, ref: ObjectRef) -> bool:
        task_id = ref.id().task_id()
        for raylet in self.cluster_state.raylets.values():
            if raylet.cancel(task_id):
                return True
        return False

    def kv_put(self, ns: str, key: bytes, value: bytes) -> None:
        with self._kv_lock:
            self.kv[(ns, key)] = value

    def kv_get(self, ns: str, key: bytes) -> Optional[bytes]:
        with self._kv_lock:
            return self.kv.get((ns, key))

    def kv_del(self, ns: str, key: bytes) -> None:
        with self._kv_lock:
            self.kv.pop((ns, key), None)

    def kv_keys(self, ns: str, prefix: bytes) -> List[bytes]:
        with self._kv_lock:
            return [k for (n, k) in self.kv if n == ns and k.startswith(prefix)]

    def nodes(self) -> List[dict]:
        out = []
        with self.cluster_state.lock:
            self.cluster_state.refresh_locked()
            for nid, raylet in self.cluster_state.raylets.items():
                slot = self.cluster_state.matrix.slot_of(nid)
                out.append({
                    "NodeID": nid.hex(),
                    "Alive": bool(self.cluster_state.matrix.alive[slot]),
                    "Resources": raylet.local_resources.to_map(
                        self.cluster_state.ids),
                })
        return out

    def cluster_resources(self) -> Dict[str, float]:
        totals: Dict[str, float] = {}
        for raylet in self.cluster_state.alive_raylets():
            for k, v in raylet.local_resources.to_map(
                    self.cluster_state.ids).items():
                totals[k] = totals.get(k, 0) + v
        return totals

    def available_resources(self) -> Dict[str, float]:
        totals: Dict[str, float] = {}
        for raylet in self.cluster_state.alive_raylets():
            for k, v in raylet.local_resources.to_map(
                    self.cluster_state.ids, available=True).items():
                totals[k] = totals.get(k, 0) + v
        return totals

    def shutdown(self) -> None:
        self.is_shutdown = True
        for rec in self.actor_directory.list():
            if rec.executor is not None:
                rec.executor.kill()
        for raylet in list(self.cluster_state.raylets.values()):
            raylet.shutdown()
        if self.process_pool is not None:
            self.process_pool.shutdown()
            self.process_pool = None
        if self._process_shm is not None:
            try:
                self._process_shm.close(unlink=True)
            except Exception as e:
                # stale-segment sweep reclaims it at the next boot
                logger.debug("driver shm segment close failed: %r", e)
            self._process_shm = None


def _normalize_runtime_env(runtime_env):
    from ray_tpu._private.runtime_env import normalize

    return normalize(runtime_env)


def init_runtime(**kwargs) -> Runtime:
    global global_runtime
    with _init_lock:
        if global_runtime is not None and not global_runtime.is_shutdown:
            raise RuntimeError("ray_tpu is already initialized")
        global_runtime = Runtime(**kwargs)
        return global_runtime


def shutdown_runtime() -> None:
    global global_runtime
    with _init_lock:
        if global_runtime is not None:
            global_runtime.shutdown()
            global_runtime = None
