"""Task and actor specifications + submission options.

Mirrors the reference's TaskSpecification (src/ray/common/task/task_spec.h)
including the SchedulingClass dedup (identical resource shapes share a
class id, used for fair dispatch and worker-lease reuse) and the
remote-decorator option surface (python/ray/remote_function.py,
python/ray/actor.py).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu._private.ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID
from ray_tpu.scheduler.resources import ResourceRequest, StringIdMap

DEFAULT_MAX_RETRIES = 3


class TaskKind(Enum):
    NORMAL = 0
    ACTOR_CREATION = 1
    ACTOR_TASK = 2


class SchedulingStrategy:
    """Base for explicit strategies (util/scheduling_strategies.py)."""


@dataclass
class NodeAffinitySchedulingStrategy(SchedulingStrategy):
    node_id: Any  # NodeID or hex string
    soft: bool = False


@dataclass
class PlacementGroupSchedulingStrategy(SchedulingStrategy):
    placement_group: Any
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


_scheduling_class_lock = threading.Lock()
_scheduling_class_ids: Dict[Tuple, int] = {}


def scheduling_class_of(req: ResourceRequest, fn_key: str = "") -> int:
    """Intern (resource shape, fn) -> dense class id
    (reference: task_spec.cc GetSchedulingClass)."""
    key = (req.key(), fn_key)
    with _scheduling_class_lock:
        cid = _scheduling_class_ids.get(key)
        if cid is None:
            cid = len(_scheduling_class_ids)
            _scheduling_class_ids[key] = cid
        return cid


class TaskTemplate:
    """Frozen per-``@remote`` submit state — the dispatch fast lane's
    preserialized task-spec template.

    Everything a plain task's submits share — the resolved resource
    map, retry policy, scheduling strategy, and (per id-map) the shared
    :class:`ResourceRequest` + interned SchedulingClass — is computed
    ONCE at decoration time, so the per-call hot loop only re-encodes
    args and mints IDs (reference: the direct task submitter's cached
    TaskSpecBuilder prototype; this extends the ``TaskSpec._req_cache``
    memo to the whole frozen form). Templates exist only for options
    where :meth:`eligible` holds: placement groups rewrite the demand
    per submit and runtime envs carry per-submit state, so those take
    the general path. ``RemoteFunction.options()`` builds a NEW
    RemoteFunction, hence a new template — stale-option reuse cannot
    happen."""

    __slots__ = ("func_name", "name", "resources", "num_returns",
                 "max_retries", "retries_left", "retry_exceptions",
                 "scheduling_strategy", "_ids", "_req",
                 "_scheduling_class")

    def __init__(self, func_name: str, options: "TaskOptions"):
        self.func_name = func_name
        self.name = options.name or func_name
        self.resources = options.resolved_resources()
        self.num_returns = options.num_returns
        self.max_retries = options.max_retries
        self.retries_left = max(0, options.max_retries)
        self.retry_exceptions = options.retry_exceptions
        strategy = options.scheduling_strategy
        self.scheduling_strategy = (None if strategy in (None, "DEFAULT")
                                    else strategy)
        self._ids: Any = None
        self._req: Any = None
        self._scheduling_class = 0

    @staticmethod
    def eligible(options: "TaskOptions") -> bool:
        return (options.placement_group is None
                and not isinstance(options.scheduling_strategy,
                                   PlacementGroupSchedulingStrategy)
                and options.runtime_env is None)

    def demand(self, ids: StringIdMap) -> Tuple[ResourceRequest, int]:
        """The template's (shared request, scheduling class) under
        ``ids``, memoized per id-map — a fresh runtime brings a fresh
        StringIdMap and recomputes once. Unsynchronized on purpose:
        racing recomputes write identical values, and ``_ids`` is
        assigned LAST so a reader that observes the new map also
        observes the request interned against it."""
        if self._ids is not ids:
            req = ResourceRequest.from_map(self.resources, ids)
            self._req = req
            self._scheduling_class = scheduling_class_of(
                req, self.func_name)
            self._ids = ids
        return self._req, self._scheduling_class


@dataclass
class TaskOptions:
    num_returns: int = 1
    num_cpus: Optional[float] = None
    num_gpus: Optional[float] = None
    num_tpus: Optional[float] = None
    memory: Optional[float] = None
    object_store_memory: Optional[float] = None
    resources: Dict[str, float] = field(default_factory=dict)
    accelerator_type: Optional[str] = None
    max_retries: int = DEFAULT_MAX_RETRIES
    retry_exceptions: Any = False  # bool or list of exception types
    max_calls: int = 0
    name: str = ""
    scheduling_strategy: Any = None  # None|"DEFAULT"|"SPREAD"|strategy obj
    placement_group: Any = None
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: Optional[bool] = None
    runtime_env: Optional[dict] = None
    _metadata: Dict[str, Any] = field(default_factory=dict)

    def resolved_resources(self) -> Dict[str, float]:
        res = dict(self.resources)
        res["CPU"] = self.num_cpus if self.num_cpus is not None else 1.0
        if self.num_gpus:
            res["GPU"] = self.num_gpus
        if self.num_tpus:
            res["TPU"] = self.num_tpus
        if self.memory:
            res["memory"] = self.memory
        if self.object_store_memory:
            res["object_store_memory"] = self.object_store_memory
        return {k: v for k, v in res.items() if v}


@dataclass
class ActorOptions:
    num_cpus: Optional[float] = None
    num_gpus: Optional[float] = None
    num_tpus: Optional[float] = None
    memory: Optional[float] = None
    resources: Dict[str, float] = field(default_factory=dict)
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: Optional[int] = None
    max_pending_calls: int = -1
    name: Optional[str] = None
    namespace: Optional[str] = None
    lifetime: Optional[str] = None  # None | "detached"
    get_if_exists: bool = False
    scheduling_strategy: Any = None
    placement_group: Any = None
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: Optional[bool] = None
    runtime_env: Optional[dict] = None
    concurrency_groups: Dict[str, int] = field(default_factory=dict)
    # reference: out_of_order_actor_scheduling_queue.cc — calls execute
    # as they arrive instead of waiting for missing sequence numbers
    execute_out_of_order: bool = False

    def placement_resources(self) -> Dict[str, float]:
        """Resources required to *create* the actor. Like the reference,
        an actor with no explicit resources needs 1 CPU to be placed but
        holds 0 while alive (actor.py _process_option_dict)."""
        res = dict(self.resources)
        res["CPU"] = self.num_cpus if self.num_cpus is not None else 1.0
        if self.num_gpus:
            res["GPU"] = self.num_gpus
        if self.num_tpus:
            res["TPU"] = self.num_tpus
        if self.memory:
            res["memory"] = self.memory
        return {k: v for k, v in res.items() if v}

    def lifetime_resources(self) -> Dict[str, float]:
        res = dict(self.resources)
        if self.num_cpus:
            res["CPU"] = self.num_cpus
        if self.num_gpus:
            res["GPU"] = self.num_gpus
        if self.num_tpus:
            res["TPU"] = self.num_tpus
        if self.memory:
            res["memory"] = self.memory
        return {k: v for k, v in res.items() if v}


@dataclass
class TaskSpec:
    kind: TaskKind
    task_id: TaskID
    job_id: JobID
    parent_task_id: TaskID
    name: str
    func: Optional[Callable] = None       # resolved callable (local mode)
    func_descriptor: str = ""             # module.qualname for remote exec
    args: Tuple = ()
    kwargs: Dict[str, Any] = field(default_factory=dict)
    num_returns: int = 1
    return_ids: Tuple[ObjectID, ...] = ()
    resources: Dict[str, float] = field(default_factory=dict)
    scheduling_class: int = 0
    scheduling_strategy: Any = None
    max_retries: int = 0
    retries_left: int = 0
    retry_exceptions: Any = False
    depth: int = 0
    owner_hex: str = ""
    # actor fields
    actor_id: Optional[ActorID] = None
    actor_creation: Optional["ActorCreationSpec"] = None
    sequence_number: int = -1
    # placement group
    placement_group_id: Optional[PlacementGroupID] = None
    placement_group_bundle_index: int = -1
    capture_child_tasks: bool = False
    # runtime environment (env_vars/working_dir/... applied around exec)
    runtime_env: Any = None
    # profiling
    submit_time: float = 0.0
    # tracing: submission-span context, so the execution span parents to
    # it across the worker boundary (reference: tracing_helper.py injects
    # the OpenTelemetry context into the task spec)
    trace_context: Optional[Dict[str, str]] = None

    # memoized dense demand: resource_request is called on the submit,
    # schedule, dispatch and free paths — build it once per spec
    _req_cache: Any = field(default=None, repr=False, compare=False)

    def resource_request(self, ids: StringIdMap) -> ResourceRequest:
        req = self._req_cache
        if req is None:
            req = ResourceRequest.from_map(self.resources, ids)
            self._req_cache = req
        return req

    def is_actor_task(self) -> bool:
        return self.kind is TaskKind.ACTOR_TASK

    def is_actor_creation(self) -> bool:
        return self.kind is TaskKind.ACTOR_CREATION


@dataclass
class ActorCreationSpec:
    actor_id: ActorID
    cls: Any
    cls_descriptor: str
    init_args: Tuple
    init_kwargs: Dict[str, Any]
    options: ActorOptions
    is_async: bool = False
    max_restarts: int = 0
    restarts_used: int = 0
