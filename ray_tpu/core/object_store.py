"""In-process object store.

Equivalent of the reference's CoreWorkerMemoryStore
(core_worker/store_provider/memory_store/memory_store.cc): holds task
results and puts for the owning process, wakes synchronous getters and
async waiters, and feeds the reference counter's eviction decisions.

TPU-first note: values are stored *by reference* (zero-copy) in-process;
serialization happens only at a process or device boundary. Large arrays
therefore move to workers/devices without a host copy, the moral
equivalent of plasma's mmap zero-copy path.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import GetTimeoutError, ObjectLostError


def _sizeof(value: Any) -> int:
    try:
        import numpy as np

        if isinstance(value, np.ndarray):
            return int(value.nbytes)
    except Exception:
        pass
    nbytes = getattr(value, "nbytes", None)
    if isinstance(nbytes, int):
        return nbytes
    if isinstance(value, (bytes, bytearray, memoryview)):
        return len(value)
    if isinstance(value, str):
        return len(value)
    return 64  # nominal


@dataclass
class StoredObject:
    value: Any = None
    is_error: bool = False
    size: int = 0
    create_time: float = field(default_factory=time.monotonic)


class MemoryStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._objects: Dict[ObjectID, StoredObject] = {}
        self._waiters: Dict[ObjectID, List[Callable[[], None]]] = {}
        self._cv = threading.Condition(self._lock)
        self.total_bytes = 0
        self.num_puts = 0

    # -- write -------------------------------------------------------------
    def put(self, object_id: ObjectID, value: Any, is_error: bool = False) -> None:
        size = _sizeof(value)
        with self._lock:
            if object_id in self._objects:
                return  # objects are immutable; first write wins
            self._objects[object_id] = StoredObject(value, is_error, size)
            self.total_bytes += size
            self.num_puts += 1
            callbacks = self._waiters.pop(object_id, ())
            self._cv.notify_all()
        for cb in callbacks:
            cb()

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            obj = self._objects.pop(object_id, None)
            if obj is not None:
                self.total_bytes -= obj.size

    # -- read --------------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def peek(self, object_id: ObjectID) -> Optional[StoredObject]:
        with self._lock:
            return self._objects.get(object_id)

    def get(
        self,
        object_ids: Sequence[ObjectID],
        timeout: Optional[float] = None,
    ) -> List[StoredObject]:
        """Block until all ids are present; returns StoredObjects in order.

        Raises GetTimeoutError on timeout (reference: CoreWorker::Get,
        core_worker.cc:1010).
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                missing = [o for o in object_ids if o not in self._objects]
                if not missing:
                    return [self._objects[o] for o in object_ids]
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise GetTimeoutError(
                            f"Get timed out: {len(missing)} of "
                            f"{len(object_ids)} objects not ready"
                        )
                    self._cv.wait(remaining)
                else:
                    self._cv.wait()

    def wait(
        self,
        object_ids: Sequence[ObjectID],
        num_returns: int,
        timeout: Optional[float],
    ) -> tuple[list[ObjectID], list[ObjectID]]:
        """ray.wait semantics: first num_returns ready (in request order),
        rest unready (reference: wait_manager / CoreWorker::Wait)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                ready = [o for o in object_ids if o in self._objects]
                if len(ready) >= num_returns:
                    ready_set = set(ready[:num_returns])
                    break
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        ready_set = set(ready)
                        break
                    self._cv.wait(remaining)
                else:
                    self._cv.wait()
            ready_list = [o for o in object_ids if o in ready_set]
            unready_list = [o for o in object_ids if o not in ready_set]
            return ready_list, unready_list

    # -- notifications -----------------------------------------------------
    def on_available(self, object_id: ObjectID, callback: Callable[[], None]
                     ) -> None:
        """Invoke callback once the object exists (immediately if present)."""
        with self._lock:
            if object_id not in self._objects:
                self._waiters.setdefault(object_id, []).append(callback)
                return
        callback()

    # -- stats -------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "num_objects": len(self._objects),
                "total_bytes": self.total_bytes,
                "num_puts": self.num_puts,
            }
