"""In-process object store.

Equivalent of the reference's CoreWorkerMemoryStore
(core_worker/store_provider/memory_store/memory_store.cc): holds task
results and puts for the owning process, wakes synchronous getters and
async waiters, and feeds the reference counter's eviction decisions.

TPU-first note: values are stored *by reference* (zero-copy) in-process;
serialization happens only at a process or device boundary. Large arrays
therefore move to workers/devices without a host copy, the moral
equivalent of plasma's mmap zero-copy path.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)

from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import GetTimeoutError, ObjectLostError


def _sizeof(value: Any) -> int:
    try:
        import numpy as np

        if isinstance(value, np.ndarray):
            return int(value.nbytes)
    except Exception as e:
        # numpy unavailable: the generic estimators below apply
        logger.debug("numpy sizeof probe failed: %r", e)
    nbytes = getattr(value, "nbytes", None)
    if isinstance(nbytes, int):
        return nbytes
    if isinstance(value, (bytes, bytearray, memoryview)):
        return len(value)
    if isinstance(value, str):
        return len(value)
    return 64  # nominal


@dataclass
class StoredObject:
    value: Any = None
    is_error: bool = False
    size: int = 0
    create_time: float = field(default_factory=time.monotonic)
    # set when the payload lives on disk, not in memory (spilled)
    spilled_path: Optional[str] = None
    # integrity plane: put-time digest of buffer-typed values (bytes/
    # ndarray), verified at get when integrity_verify_on_get is on;
    # spill files carry their own digest in the file header
    crc: Optional[int] = None


class MemoryStore:
    def __init__(self, capacity: Optional[int] = None,
                 spill_directory: Optional[str] = None,
                 spill_threshold: Optional[float] = None):
        from ray_tpu._private.config import Config

        cfg = Config.instance()
        self._lock = threading.Lock()
        self._objects: Dict[ObjectID, StoredObject] = {}
        self._waiters: Dict[ObjectID, List[Callable[[], None]]] = {}
        self._cv = threading.Condition(self._lock)
        self._cv_waiters = 0  # gate notify_all on the put hot path
        self.total_bytes = 0
        self.num_puts = 0
        self.capacity = capacity or cfg.object_store_memory
        self.spill_threshold = (spill_threshold
                                if spill_threshold is not None
                                else cfg.object_spilling_threshold)
        self._spill_dir = spill_directory or cfg.spill_directory or None
        self.num_spilled = 0
        self.num_restored = 0
        self.spilled_bytes = 0
        # integrity plane: spilled copies dropped on a failed digest
        self.num_corrupt_dropped = 0
        # admission control for restores (scheduler/pull_manager.py);
        # attached by the runtime, None -> restore immediately
        self.pull_manager = None

    # -- write -------------------------------------------------------------
    def put(self, object_id: ObjectID, value: Any, is_error: bool = False) -> None:
        size = _sizeof(value)
        # in-process values are held by reference (zero-copy) — there
        # is no byte seam to protect at put time, so the put digest is
        # computed only when the verify-on-get knob asks for the
        # end-to-end re-check (and only for buffer-typed values, which
        # have a stable byte representation). Spill files always carry
        # their own digest, computed at spill time.
        crc = None
        if not is_error:
            from ray_tpu.cluster import integrity

            if integrity.verify_on_get():
                crc = integrity.checksum_value(value)
        with self._lock:
            if object_id in self._objects:
                return  # objects are immutable; first write wins
            self._objects[object_id] = StoredObject(value, is_error,
                                                    size, crc=crc)
            self.total_bytes += size
            self.num_puts += 1
            callbacks = self._waiters.pop(object_id, ())
            if self._cv_waiters:
                self._cv.notify_all()
        for cb in callbacks:
            cb()
        if self.total_bytes > self.capacity * self.spill_threshold:
            self._spill_until_under()

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            obj = self._objects.pop(object_id, None)
            if obj is not None:
                if obj.spilled_path is None:
                    self.total_bytes -= obj.size
                else:
                    self._delete_spill_file(obj)

    # -- spilling ----------------------------------------------------------
    # Reference: raylet/local_object_manager.h SpillObjects — when the
    # store crosses the threshold, the oldest unspilled objects move to
    # external storage; reads transparently restore them.
    def _spill_dir_path(self) -> str:
        import os

        if self._spill_dir is None:
            import tempfile

            # pid in the name: cluster/byte_store.sweep_stale_segments
            # reclaims spill dirs by parsing the owner pid from it (a
            # pid-less random suffix would be unsweepable — or worse,
            # misparsed)
            self._spill_dir = tempfile.mkdtemp(
                prefix=f"ray_tpu_spill_{os.getpid()}_")
        else:
            os.makedirs(self._spill_dir, exist_ok=True)
        return self._spill_dir

    def _spill_until_under(self) -> None:
        target = self.capacity * self.spill_threshold
        while True:
            with self._lock:
                if self.total_bytes <= target:
                    return
                candidates = [
                    (oid, obj) for oid, obj in self._objects.items()
                    if obj.spilled_path is None and not obj.is_error
                    and obj.size >= 1024]
                if not candidates:
                    return
                oid, obj = min(candidates,
                               key=lambda kv: kv[1].create_time)
            self._spill_one(oid, obj)

    def _spill_one(self, object_id: ObjectID, obj: StoredObject) -> None:
        import os

        try:
            import cloudpickle as pickle
        except ImportError:  # pragma: no cover
            import pickle
        from ray_tpu.cluster import fault_plane as _fault
        from ray_tpu.cluster import integrity

        path = os.path.join(self._spill_dir_path(),
                            f"{object_id.hex()}.spill")
        # Two threads can race here for the same object: each put() past
        # the threshold runs _spill_until_under, and candidate selection
        # happens under the lock while the write happens outside it. Both
        # losers used to unlink the *shared* per-object path, deleting the
        # winner's just-recorded spill file and leaving spilled_path
        # dangling. Each spiller therefore writes a private tmp file and
        # only the lock winner os.replace()s it onto the canonical path;
        # a loser can only ever remove its own tmp.
        tmp = f"{path}.{threading.get_ident()}.tmp"
        try:
            data = pickle.dumps(obj.value)
        except Exception:  # unpicklable values just stay resident
            return
        # integrity plane: digest of the pickled payload rides the
        # spill-file header and is verified at restore — a flipped bit
        # at rest becomes a typed error + lineage recompute, not a
        # silently-wrong get
        crc = integrity.checksum(data) if integrity.enabled() else None
        plane = _fault.get_plane()
        if plane is not None:
            fault = plane.decide("spill", "memory_store",
                                 object_id.hex())
            if fault is not None and fault["action"] == "corrupt":
                data = _fault.apply_corruption(data, fault)
        try:
            with open(tmp, "wb") as f:
                f.write(integrity.pack_spill_header(False, crc))
                f.write(data)
        except Exception:
            return
        with self._lock:
            cur = self._objects.get(object_id)
            if cur is not obj or obj.spilled_path is not None:
                os.unlink(tmp)
                return
            os.replace(tmp, path)
            obj.spilled_path = path
            obj.value = None
            self.total_bytes -= obj.size
            self.spilled_bytes += obj.size
            self.num_spilled += 1

    def _restore(self, object_id: ObjectID, obj: StoredObject) -> None:
        try:
            import cloudpickle as pickle
        except ImportError:  # pragma: no cover
            import pickle
        from ray_tpu.cluster import integrity
        from ray_tpu.exceptions import ObjectCorruptedError

        with open(obj.spilled_path, "rb") as f:
            raw = f.read()
        try:
            _, payload, crc = integrity.parse_spill(raw)
            integrity.verify(payload, crc, "spill_restore",
                             bytes(object_id.binary())
                             if hasattr(object_id, "binary") else b"")
        except (ObjectCorruptedError, ValueError) as err:
            # failed digest or torn header: the spilled copy is gone
            # for good — drop the OBJECT (its bytes are unrecoverable
            # here) and surface the typed error; Runtime.get recovers
            # via lineage reconstruction
            with self._lock:
                cur = self._objects.get(object_id)
                if cur is obj and obj.spilled_path is not None:
                    self._delete_spill_file(obj)
                    self._objects.pop(object_id, None)
                    self.spilled_bytes -= obj.size
                    obj.spilled_path = None
            self.num_corrupt_dropped += 1
            if isinstance(err, ObjectCorruptedError):
                raise
            integrity.record_corruption("spill_restore")
            raise ObjectCorruptedError(
                object_id.hex(), "spill_restore",
                f"spill file of {object_id.hex()[:16]} unreadable: "
                f"{err!r}") from err
        value = pickle.loads(payload)
        with self._lock:
            if obj.spilled_path is None:
                return
            self._delete_spill_file(obj)
            obj.value = value
            obj.spilled_path = None
            self.total_bytes += obj.size
            self.spilled_bytes -= obj.size
            self.num_restored += 1

    def _delete_spill_file(self, obj: StoredObject) -> None:
        import os

        try:
            os.unlink(obj.spilled_path)
        except OSError as e:
            logger.debug("removing spill file %s failed: %r",
                         obj.spilled_path, e)

    def _materialized(self, object_id: ObjectID,
                      obj: StoredObject) -> StoredObject:
        if obj.spilled_path is not None:
            self._restore(object_id, obj)
        return obj

    def restore_spilled(self, object_ids: Sequence[ObjectID],
                        priority=None,
                        timeout: Optional[float] = None) -> None:
        """Restore any spilled objects among `object_ids`, gated by the
        pull manager's admission queue when one is attached (reference:
        PullManager activation triggers spill-restore for local spilled
        objects, pull_manager.cc). With a finite timeout, failing to win
        admission in time raises GetTimeoutError — it never restores
        around the admission gate."""
        with self._lock:
            spilled = [(oid, self._objects[oid]) for oid in object_ids
                       if oid in self._objects
                       and self._objects[oid].spilled_path is not None]
        if not spilled:
            return
        pm = self.pull_manager
        if pm is None:
            for oid, obj in spilled:
                self._restore(oid, obj)
            return
        from ray_tpu.scheduler.pull_manager import BundlePriority

        if priority is None:
            priority = BundlePriority.GET_REQUEST
        bundle_id = pm.pull(priority, object_ids,
                            [obj.size for _, obj in spilled])
        try:
            if not pm.wait_active(bundle_id, timeout) and \
                    timeout is not None:
                raise GetTimeoutError(
                    f"restore of {len(spilled)} spilled objects not "
                    f"admitted within {timeout}s")
            for oid, obj in spilled:
                self._restore(oid, obj)
        finally:
            pm.cancel(bundle_id)

    # -- read --------------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def peek(self, object_id: ObjectID) -> Optional[StoredObject]:
        with self._lock:
            obj = self._objects.get(object_id)
        if obj is None:
            return None
        return self._materialized(object_id, obj)

    def get(
        self,
        object_ids: Sequence[ObjectID],
        timeout: Optional[float] = None,
    ) -> List[StoredObject]:
        """Block until all ids are present; returns StoredObjects in order.

        Raises GetTimeoutError on timeout (reference: CoreWorker::Get,
        core_worker.cc:1010).
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            # Countdown latch over the per-object waiter callbacks: one
            # event wake when the LAST missing object lands, instead of a
            # notify_all + O(missing) rescan per put. Constructed only
            # when something is actually missing.
            with self._lock:
                missing = [o for o in object_ids if o not in self._objects]
                if not missing:
                    found = [self._objects[o] for o in object_ids]
                    break
                latch_lock = threading.Lock()
                done = threading.Event()
                state = {"n": len(missing)}

                def _one_ready():
                    with latch_lock:
                        state["n"] -= 1
                        if state["n"] == 0:
                            done.set()

                for oid in missing:
                    self._waiters.setdefault(oid, []).append(_one_ready)
            if deadline is None:
                done.wait()
            else:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not done.wait(remaining):
                    with self._lock:  # unregister our callbacks
                        for oid in missing:
                            cbs = self._waiters.get(oid)
                            if cbs is not None:
                                try:
                                    cbs.remove(_one_ready)
                                except ValueError as e:
                                    # a concurrent ready-callback
                                    # already consumed the entry
                                    logger.debug(
                                        "wait callback for %s already "
                                        "removed: %r", oid, e)
                                if not cbs:
                                    self._waiters.pop(oid, None)
                        still = sum(1 for o in object_ids
                                    if o not in self._objects)
                    if still == 0:
                        # everything landed right at the deadline: the
                        # top-of-loop rescan will collect and return it
                        continue
                    raise GetTimeoutError(
                        f"Get timed out: {still} of "
                        f"{len(object_ids)} objects not ready"
                    )
            # loop: revalidate the FULL list — an initially-present
            # object may have been evicted while we waited
        remaining = (None if deadline is None
                     else max(0.0, deadline - time.monotonic()))
        self.restore_spilled(object_ids, timeout=remaining)
        out = [self._materialized(oid, o)
               for oid, o in zip(object_ids, found)]
        from ray_tpu.cluster import integrity

        if integrity.verify_on_get():
            # knob-gated end-to-end re-check at deserialization: a
            # buffer value mutated in place between put and get fails
            # its put-time digest here
            for oid, obj in zip(object_ids, out):
                if obj.crc is not None and not obj.is_error:
                    actual = integrity.checksum_value(obj.value)
                    if actual is not None and actual != obj.crc:
                        from ray_tpu.exceptions import (
                            ObjectCorruptedError,
                        )

                        integrity.record_corruption("get")
                        raise ObjectCorruptedError(
                            oid.hex(), "get",
                            f"object {oid.hex()[:16]} failed its "
                            f"put-time digest at get")
        return out

    def wait(
        self,
        object_ids: Sequence[ObjectID],
        num_returns: int,
        timeout: Optional[float],
    ) -> tuple[list[ObjectID], list[ObjectID]]:
        """ray.wait semantics: first num_returns ready (in request order),
        rest unready (reference: wait_manager / CoreWorker::Wait)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                ready = [o for o in object_ids if o in self._objects]
                if len(ready) >= num_returns:
                    ready_set = set(ready[:num_returns])
                    break
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        ready_set = set(ready)
                        break
                    self._cv_waiters += 1
                    try:
                        self._cv.wait(remaining)
                    finally:
                        self._cv_waiters -= 1
                else:
                    self._cv_waiters += 1
                    try:
                        self._cv.wait()
                    finally:
                        self._cv_waiters -= 1
            ready_list = [o for o in object_ids if o in ready_set]
            unready_list = [o for o in object_ids if o not in ready_set]
            return ready_list, unready_list

    def peek(self, object_id: ObjectID):
        """Non-materializing lookup: the StoredObject if resident (its
        ``is_error``/``value`` let completion hooks classify an outcome
        without a full get), else None. Does not restore spills."""
        with self._lock:
            return self._objects.get(object_id)

    # -- notifications -----------------------------------------------------
    def on_available(self, object_id: ObjectID, callback: Callable[[], None]
                     ) -> None:
        """Invoke callback once the object exists (immediately if present)."""
        with self._lock:
            if object_id not in self._objects:
                self._waiters.setdefault(object_id, []).append(callback)
                return
        callback()

    # -- stats -------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "num_objects": len(self._objects),
                "total_bytes": self.total_bytes,
                "num_puts": self.num_puts,
                "num_spilled": self.num_spilled,
                "num_restored": self.num_restored,
                "spilled_bytes": self.spilled_bytes,
                "num_corrupt_dropped": self.num_corrupt_dropped,
            }
