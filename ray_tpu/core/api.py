"""Public API: init/remote/get/put/wait and the decorator plumbing.

Mirrors the reference's python surface (python/ray/worker.py:636,1778,
1872,1925,2272; remote_function.py; actor.py): ``@remote`` wraps functions
into RemoteFunction and classes into ActorClass; ``.options(...)``
produces a one-shot override; actor handles expose ``.method.remote()``.
"""

from __future__ import annotations

import functools
import inspect
import logging
from dataclasses import replace as dc_replace
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ray_tpu._private.config import Config
from ray_tpu.core import runtime as rt_mod
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import ActorOptions, TaskOptions, TaskTemplate
from ray_tpu.exceptions import RayTpuError

logger = logging.getLogger(__name__)

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "method", "nodes", "cluster_resources",
    "available_resources", "get_runtime_context", "ObjectRef",
]


# --------------------------------------------------------------------- init
def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_gpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    object_store_memory: Optional[int] = None,
    namespace: Optional[str] = None,
    ignore_reinit_error: bool = False,
    _system_config: Optional[dict] = None,
    **kwargs,
):
    """Start (or connect to) the runtime. With no address, brings up an
    in-process cluster (reference: ray.init starting a local node).
    ``address="ray://host:port"`` enters CLIENT MODE against a running
    client server (reference: ray client, ray.init("ray://...")): the
    module-level verbs (remote/get/put/wait/kill) proxy over the wire
    until shutdown()."""
    global _client_ctx
    if address is not None and str(address).startswith("ray://"):
        if _client_ctx is not None and _client_ctx.connected:
            if ignore_reinit_error:
                return _client_ctx
            raise RuntimeError(
                "ray_tpu.init() called twice; pass ignore_reinit_error=True")
        if (rt_mod.global_runtime is not None
                and not rt_mod.global_runtime.is_shutdown):
            raise RuntimeError(
                "cannot enter ray:// client mode while a local runtime "
                "is active; call ray_tpu.shutdown() first")
        from ray_tpu.util.client.client import connect

        _client_ctx = connect(address)
        return _client_ctx
    if rt_mod.global_runtime is not None and not rt_mod.global_runtime.is_shutdown:
        if ignore_reinit_error:
            logger.info("ray_tpu is already initialized; ignoring re-init")
            return rt_mod.global_runtime
        raise RuntimeError(
            "ray_tpu.init() called twice; pass ignore_reinit_error=True")
    if _client_ctx is not None and _client_ctx.connected:
        # Mirror of the client-mode guard above: a local init while a
        # ray:// connection is open would make _client() silently prefer
        # the local runtime, shadowing the still-open client connection.
        if ignore_reinit_error:
            return _client_ctx
        raise RuntimeError(
            "cannot start a local runtime while a ray:// client "
            "connection is active; call ray_tpu.shutdown() first")
    if _system_config:
        Config.instance().apply_system_config(_system_config)
    tracing_hook = kwargs.pop("_tracing_startup_hook", None)
    if tracing_hook is not None:
        # reference: worker.py:666 — a callable (or "module:attr" import
        # string) that configures the tracer before any spans start
        if isinstance(tracing_hook, str):
            import importlib

            mod_name, _, attr = tracing_hook.partition(":")
            tracing_hook = getattr(importlib.import_module(mod_name), attr)
        tracing_hook()
    return rt_mod.init_runtime(
        num_cpus=num_cpus,
        num_gpus=num_gpus,
        resources=resources,
        object_store_memory=object_store_memory,
        namespace=namespace,
        worker_mode=kwargs.pop("worker_mode", "thread"),
        num_process_workers=kwargs.pop("num_process_workers", None),
    )


_client_ctx = None  # set by init(address="ray://...")


def _client():
    if _client_ctx is None or not _client_ctx.connected:
        return None
    # A live LOCAL runtime wins: this process IS (part of) the cluster —
    # e.g. the client server itself, or a worker executing tasks — and
    # its own api calls must never bounce back over the wire.
    rt = rt_mod.global_runtime
    if rt is not None and not rt.is_shutdown:
        return None
    return _client_ctx


def shutdown() -> None:
    global _client_ctx
    if _client_ctx is not None:
        _client_ctx.disconnect()
        _client_ctx = None
        return
    rt_mod.shutdown_runtime()
    Config.reset()


def is_initialized() -> bool:
    if _client() is not None:
        return True
    return (rt_mod.global_runtime is not None
            and not rt_mod.global_runtime.is_shutdown)


def _runtime():
    rt = rt_mod.global_runtime
    if rt is None or rt.is_shutdown:
        if _client() is not None:
            # loud failure beats silently auto-initing a second,
            # unrelated local cluster underneath a connected client
            raise RuntimeError(
                "this API is not proxied in ray:// client mode; use the "
                "core verbs (remote/get/put/wait/kill) or run against a "
                "local runtime")
        # auto-init like the reference does on first remote call
        return init()
    return rt


# ------------------------------------------------------- remote functions
class RemoteFunction:
    def __init__(self, func, options: TaskOptions):
        self._func = func
        self._options = options
        self._name = getattr(func, "__qualname__", str(func))
        self._module = getattr(func, "__module__", "")
        self._descriptor = f"{self._module}.{self._name}"
        # dispatch fast lane: freeze the per-submit constants at
        # decoration time (options()/client mode rebuild/skip it)
        self._template = (
            TaskTemplate(self._descriptor, options)
            if TaskTemplate.eligible(options) else None)
        functools.update_wrapper(self, func)

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        ctx = _client()
        if ctx is not None:
            # client mode binds at CALL time: decoration commonly
            # happens at import, before init("ray://...") connects
            return ctx.remote(
                self._func,
                **_nondefault_options(self._options, TaskOptions())
            ).remote(*args, **kwargs)
        return self._remote(args, kwargs, self._options)

    def options(self, **overrides) -> "RemoteFunction":
        opts = dc_replace(self._options, **{
            k: v for k, v in overrides.items()
            if hasattr(self._options, k)})
        unknown = [k for k in overrides if not hasattr(self._options, k)]
        if unknown:
            raise ValueError(f"unknown option(s): {unknown}")
        return RemoteFunction(self._func, opts)

    def _remote(self, args, kwargs, opts: TaskOptions):
        rt = _runtime()
        refs = rt.submit_task(
            self._func, self._descriptor, args, kwargs, opts,
            template=self._template)
        if opts.num_returns == 1:
            return refs[0]
        if opts.num_returns == 0:
            return None
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function cannot be called directly; use "
            f"{self._name}.remote()")


# ----------------------------------------------------------------- actors
class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1, concurrency_group: str = ""):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def remote(self, *args, **kwargs):
        return self._handle._submit(
            self._method_name, args, kwargs, self._num_returns,
            self._concurrency_group)

    def options(self, num_returns: Optional[int] = None,
                concurrency_group: str = "", **_ignored) -> "ActorMethod":
        return ActorMethod(
            self._handle, self._method_name,
            num_returns if num_returns is not None else self._num_returns,
            concurrency_group or self._concurrency_group)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method cannot be called directly; use "
            f".{self._method_name}.remote()")


class ActorHandle:
    def __init__(self, record):
        object.__setattr__(self, "_record", record)

    @property
    def _actor_id(self):
        return self._record.actor_id

    def __getattr__(self, name: str):
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        cls = self._record.creation_spec.cls
        attr = getattr(cls, name, None)
        if attr is None or not callable(attr):
            raise AttributeError(
                f"actor {cls.__name__} has no method {name!r}")
        meta = getattr(attr, "__ray_tpu_method_options__", {})
        method = ActorMethod(
            self, name,
            num_returns=meta.get("num_returns", 1),
            concurrency_group=meta.get("concurrency_group", ""))
        # cache: repeated a.method lookups skip this __getattr__ entirely
        object.__setattr__(self, name, method)
        return method

    def _submit(self, method_name, args, kwargs, num_returns,
                concurrency_group=""):
        rt = _runtime()
        refs = rt.submit_actor_task(
            self._record, method_name, args, kwargs, num_returns,
            concurrency_group)
        if num_returns == 1:
            return refs[0]
        if num_returns == 0:
            return None
        return refs

    def __repr__(self):
        return (f"ActorHandle({self._record.creation_spec.cls_descriptor}, "
                f"{self._actor_id.hex()[:12]})")

    def __reduce__(self):
        # handles are shareable: identity is the actor id, resolved against
        # the directory on deserialization
        return (_rehydrate_handle, (self._actor_id,))


def _rehydrate_handle(actor_id):
    rt = _runtime()
    record = rt.actor_directory.get(actor_id)
    if record is None:
        raise RayTpuError(f"unknown actor {actor_id.hex()}")
    return ActorHandle(record)


class ActorClass:
    def __init__(self, cls, options: ActorOptions):
        self._cls = cls
        self._options = options
        self._name = getattr(cls, "__qualname__", str(cls))
        self._module = getattr(cls, "__module__", "")

    def remote(self, *args, **kwargs) -> ActorHandle:
        ctx = _client()
        if ctx is not None:  # call-time client binding, like tasks
            return ctx.remote(
                self._cls,
                **_nondefault_options(self._options, ActorOptions())
            ).remote(*args, **kwargs)
        rt = _runtime()
        opts = self._options
        if opts.name and opts.get_if_exists:
            # Atomic get-or-create: lookup, and when the create races
            # with a concurrent creator of the same name (the directory
            # rejects the second register before any side effect), fall
            # back to the winner's actor. Reference: ray actor.py
            # _remote get_if_exists catches the creation conflict the
            # same way; two train workers bootstrapping one collective
            # coordinator hit this every few runs on a single core.
            from ray_tpu.core.actor_runtime import ActorState

            last_err = None
            for _ in range(16):  # bounded: a non-race error must surface
                existing = rt.actor_directory.get_by_name(
                    opts.name, opts.namespace or rt.namespace)
                if existing is not None and \
                        existing.state is not ActorState.DEAD:
                    return ActorHandle(existing)
                try:
                    record = rt.create_actor(
                        self._cls, f"{self._module}.{self._name}", args,
                        kwargs, opts)
                    return ActorHandle(record)
                except ValueError as e:
                    if "already taken" not in str(e):
                        raise
                    last_err = e  # lost the race; fetch the winner
            raise last_err
        record = rt.create_actor(
            self._cls, f"{self._module}.{self._name}", args, kwargs, opts)
        return ActorHandle(record)

    def options(self, **overrides) -> "ActorClass":
        opts = dc_replace(self._options, **{
            k: v for k, v in overrides.items() if hasattr(self._options, k)})
        unknown = [k for k in overrides if not hasattr(self._options, k)]
        if unknown:
            raise ValueError(f"unknown option(s): {unknown}")
        return ActorClass(self._cls, opts)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class cannot be instantiated directly; use "
            f"{self._name}.remote()")


# ------------------------------------------------------------- decorators
def _nondefault_options(opts, defaults) -> Dict[str, Any]:
    """TaskOptions/ActorOptions -> the kwargs the user actually set
    (for re-decorating on the far side of a client connection)."""
    out = {}
    for field in opts.__dataclass_fields__:
        value = getattr(opts, field)
        if value != getattr(defaults, field):
            out[field] = value
    return out


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_cpus=..., ...)`` for functions and
    classes (reference: worker.py:2272 ray.remote). Binding to client
    mode happens at CALL time inside RemoteFunction/ActorClass, so
    import-time decoration works regardless of when init("ray://...")
    connects."""

    def _make(target):
        if inspect.isclass(target):
            field_names = ActorOptions.__dataclass_fields__.keys()
            opts = ActorOptions(**{
                k: v for k, v in kwargs.items() if k in field_names})
            _check_unknown(kwargs, field_names, target)
            return ActorClass(target, opts)
        field_names = TaskOptions.__dataclass_fields__.keys()
        opts = TaskOptions(**{
            k: v for k, v in kwargs.items() if k in field_names})
        _check_unknown(kwargs, field_names, target)
        return RemoteFunction(target, opts)

    if len(args) == 1 and not kwargs and callable(args[0]):
        # any callable qualifies: plain/builtin functions, classes,
        # functools.partial (reference wraps builtins the same way)
        return _make(args[0])
    if args:
        raise TypeError("@remote takes keyword arguments only")
    return _make


def _check_unknown(kwargs, field_names, target):
    unknown = [k for k in kwargs if k not in field_names]
    if unknown:
        raise ValueError(
            f"unknown @remote option(s) {unknown} for {target}")


def method(**kwargs):
    """``@method(num_returns=2)`` on actor methods
    (reference: actor.py ray.method)."""

    def _wrap(fn):
        fn.__ray_tpu_method_options__ = kwargs
        return fn

    return _wrap


# ------------------------------------------------------------ data plane
def put(value: Any) -> ObjectRef:
    ctx = _client()
    if ctx is not None:
        return ctx.put(value)
    if isinstance(value, ObjectRef):
        raise TypeError("Calling put() on an ObjectRef is not allowed")
    return _runtime().put(value)


def get(refs, timeout: Optional[float] = None, _skip_wait: bool = False):
    ctx = _client()
    if ctx is not None:
        return ctx.get(refs, timeout=timeout)
    rt = _runtime()
    if isinstance(refs, ObjectRef):
        return rt.get([refs], timeout)[0]
    if isinstance(refs, (list, tuple)):
        bad = [r for r in refs if not isinstance(r, ObjectRef)]
        if bad:
            raise TypeError(
                f"get() expects ObjectRefs, got {type(bad[0]).__name__}")
        return rt.get(list(refs), timeout)
    raise TypeError(f"get() expects an ObjectRef or list, got {type(refs)}")


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True
         ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    ctx = _client()
    if ctx is not None:
        return ctx.wait(list(refs), num_returns=num_returns,
                        timeout=timeout)
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    refs = list(refs)
    if len(set(refs)) != len(refs):
        raise ValueError("wait() expects a list of unique ObjectRefs")
    if num_returns <= 0 or num_returns > len(refs):
        raise ValueError(
            f"num_returns ({num_returns}) must be in [1, {len(refs)}]")
    return _runtime().wait(refs, num_returns, timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    ctx = _client()
    if ctx is not None:
        ctx.kill(actor, no_restart=no_restart)
        return
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an actor handle; for tasks use cancel()")
    _runtime().kill_actor(actor._record, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True
           ) -> None:
    _runtime().cancel_task(ref)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    rt = _runtime()
    from ray_tpu.core.actor_runtime import ActorState

    record = rt.actor_directory.get_by_name(name, namespace or rt.namespace)
    if record is None or record.state is ActorState.DEAD:
        raise ValueError(f"Failed to look up actor with name {name!r}")
    return ActorHandle(record)


# ---------------------------------------------------------- introspection
def nodes() -> List[dict]:
    return _runtime().nodes()


def cluster_resources() -> Dict[str, float]:
    return _runtime().cluster_resources()


def available_resources() -> Dict[str, float]:
    return _runtime().available_resources()


class RuntimeContext:
    def __init__(self, rt):
        self._rt = rt

    @property
    def job_id(self):
        return self._rt.job_id

    @property
    def namespace(self):
        return self._rt.namespace

    def get_task_id(self):
        return self._rt.context().task_id

    def get_actor_id(self):
        aid = self._rt.context().actor_id
        return aid.hex() if aid else None

    def get_node_id(self):
        nid = self._rt.context().node_id
        return nid.hex() if nid else None

    def get_worker_id(self):
        wid = self._rt.context().worker_id
        return wid.hex() if wid else None

    def get_assigned_resources(self):
        return dict(self._rt.context().assigned_resources)

    @property
    def was_current_actor_reconstructed(self):
        aid = self._rt.context().actor_id
        if aid is None:
            return False
        rec = self._rt.actor_directory.get(aid)
        return bool(rec and rec.num_restarts > 0)


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_runtime())
