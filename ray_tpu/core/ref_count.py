"""Ownership-based distributed reference counting.

Mirrors the reference's ReferenceCounter (core_worker/reference_count.h:56):
the *owner* of an object (the process that created it) tracks

  - local refs:      live ObjectRef instances in this process
  - submitted refs:  in-flight tasks that take the object as an argument
  - borrower refs:   other processes holding deserialized copies of the ref
  - lineage refs:    tasks whose re-execution (reconstruction) needs it

An object is evictable when local + submitted + borrowers == 0; its lineage
entry is releasable when lineage refs also hit zero. Thread-safe; eviction
is delegated to a callback so the store and the counter stay decoupled
(the reference wires this the same way: on_object_evicted callbacks).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Set

from ray_tpu._private.ids import ObjectID, TaskID


@dataclass
class Reference:
    local: int = 0
    submitted: int = 0
    lineage: int = 0
    borrowers: Set[str] = field(default_factory=set)  # worker hexes
    owned: bool = False
    # The task that creates this object — lineage pointer for
    # reconstruction (reference: reference_count.h owned_by_us/lineage).
    creating_task: Optional[TaskID] = None
    pinned: bool = False  # e.g. held by the store for a pending get

    def total(self) -> int:
        return self.local + self.submitted + len(self.borrowers)


class ReferenceCounter:
    def __init__(self, on_evict: Optional[Callable[[ObjectID], None]] = None,
                 on_lineage_released: Optional[Callable[[TaskID], None]] = None):
        self._lock = threading.Lock()
        self._refs: Dict[ObjectID, Reference] = {}
        self._on_evict = on_evict
        self._on_lineage_released = on_lineage_released

    def set_eviction_callback(self, cb: Callable[[ObjectID], None]) -> None:
        self._on_evict = cb

    # -- registration ------------------------------------------------------
    def add_owned_object(self, object_id: ObjectID,
                         creating_task: Optional[TaskID] = None) -> None:
        with self._lock:
            ref = self._refs.setdefault(object_id, Reference())
            ref.owned = True
            ref.creating_task = creating_task

    def add_local_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            self._refs.setdefault(object_id, Reference()).local += 1

    def remove_local_ref(self, object_id: ObjectID) -> None:
        self._decrement(object_id, "local")

    def add_submitted_task_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            self._refs.setdefault(object_id, Reference()).submitted += 1

    def remove_submitted_task_ref(self, object_id: ObjectID) -> None:
        self._decrement(object_id, "submitted")

    def add_borrower(self, object_id: ObjectID, worker_hex: str) -> None:
        with self._lock:
            self._refs.setdefault(object_id, Reference()).borrowers.add(worker_hex)

    def remove_borrower(self, object_id: ObjectID, worker_hex: str) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            ref.borrowers.discard(worker_hex)
            evict = self._maybe_release_locked(object_id, ref)
        self._run_evict(evict)

    def add_lineage_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            self._refs.setdefault(object_id, Reference()).lineage += 1

    def remove_lineage_ref(self, object_id: ObjectID) -> None:
        released_task = None
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            ref.lineage = max(0, ref.lineage - 1)
            if ref.total() == 0 and ref.lineage == 0:
                self._refs.pop(object_id, None)
                released_task = ref.creating_task
        if released_task is not None and self._on_lineage_released:
            self._on_lineage_released(released_task)

    def pin(self, object_id: ObjectID, pinned: bool = True) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None:
                ref.pinned = pinned

    # -- queries -----------------------------------------------------------
    def local_ref_count(self, object_id: ObjectID) -> int:
        with self._lock:
            ref = self._refs.get(object_id)
            return ref.local if ref else 0

    def is_owned(self, object_id: ObjectID) -> bool:
        with self._lock:
            ref = self._refs.get(object_id)
            return bool(ref and ref.owned)

    def creating_task(self, object_id: ObjectID) -> Optional[TaskID]:
        with self._lock:
            ref = self._refs.get(object_id)
            return ref.creating_task if ref else None

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._refs)

    def dump(self) -> dict:
        """Ownership table dump for `memory` introspection
        (reference: internal/internal_api.py ray memory)."""
        with self._lock:
            return {
                oid.hex(): {
                    "local": r.local,
                    "submitted": r.submitted,
                    "borrowers": len(r.borrowers),
                    "lineage": r.lineage,
                    "owned": r.owned,
                    "pinned": r.pinned,
                }
                for oid, r in self._refs.items()
            }

    # -- internals ---------------------------------------------------------
    def _decrement(self, object_id: ObjectID, kind: str) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            setattr(ref, kind, max(0, getattr(ref, kind) - 1))
            evict = self._maybe_release_locked(object_id, ref)
        self._run_evict(evict)

    def _maybe_release_locked(self, object_id: ObjectID, ref: Reference
                              ) -> Optional[ObjectID]:
        if ref.total() == 0 and not ref.pinned:
            if ref.lineage == 0:
                self._refs.pop(object_id, None)
            return object_id
        return None

    def _run_evict(self, object_id: Optional[ObjectID]) -> None:
        if object_id is not None and self._on_evict is not None:
            self._on_evict(object_id)
