"""Actor execution: ordered delivery, concurrency, restarts.

Re-implements the reference's direct actor transport + scheduling queues
(core_worker/transport/direct_actor_task_submitter.cc,
actor_scheduling_queue.cc, fiber.h, concurrency_group_manager.cc):

  - actor method calls bypass the raylet: the caller enqueues straight to
    the actor's executor with a per-caller sequence number; a sync actor
    executes strictly in sequence-number order, max_concurrency>1 relaxes
    that within the declared window, and async actors interleave
    coroutines on a dedicated event loop capped by a semaphore.
  - while the actor is pending creation or restarting, calls buffer
    client-side and flush on ALIVE (direct_actor_task_submitter.cc
    pending-queue behavior).
  - the actor FSM matches src/ray/design_docs/actor_states.rst:
    DEPENDENCIES_UNREADY -> PENDING_CREATION -> ALIVE <-> RESTARTING -> DEAD.
"""

from __future__ import annotations

import asyncio
import heapq
import inspect
import logging
import threading
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private.config import Config
from ray_tpu._private.ids import ActorID, NodeID
from ray_tpu.cluster.threads import ThreadRegistry
from ray_tpu.exceptions import (
    ActorDiedError,
    AsyncioActorExit,
    PendingCallsLimitExceeded,
    RayActorError,
)

logger = logging.getLogger(__name__)


class ActorState(Enum):
    DEPENDENCIES_UNREADY = 0
    PENDING_CREATION = 1
    ALIVE = 2
    RESTARTING = 3
    DEAD = 4


@dataclass(order=True)
class _QueuedCall:
    seq_no: int
    # non-ordering payload:
    method_name: str = field(compare=False, default="")
    execute: Callable[[], None] = field(compare=False, default=None)
    fail: Optional[Callable[[], None]] = field(compare=False, default=None)


class ActorExecutor:
    """Runs one actor instance's methods with ordering guarantees."""

    def __init__(self, actor_id: ActorID, instance: Any,
                 max_concurrency: int, is_async: bool,
                 concurrency_groups: Optional[Dict[str, int]] = None,
                 execute_out_of_order: bool = False):
        self.actor_id = actor_id
        self.instance = instance
        self.is_async = is_async
        self.max_concurrency = max_concurrency
        # reference out_of_order_actor_scheduling_queue.cc: dispatch in
        # ARRIVAL order — never park waiting for a missing seq_no (a
        # caller whose earlier call is still resolving dependencies must
        # not head-of-line-block the actor when the user opted out of
        # ordering)
        self.execute_out_of_order = execute_out_of_order
        self.dead = False
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._heap: List[_QueuedCall] = []
        self._next_seq = 0
        self._inflight = 0
        self._async_pending = 0
        # executor threads spawn through the registry: kill() joins
        # them by name so a method wedged past death is WARN-logged
        # instead of silently leaking (raycheck RC09)
        self._threads = ThreadRegistry(f"actor-{actor_id.hex()[:6]}")
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._sem: Optional[asyncio.Semaphore] = None
        self._group_sems: Dict[str, asyncio.Semaphore] = {}
        self._group_pools: Dict[str, "ActorExecutor"] = {}
        if is_async:
            self._start_async_loop(concurrency_groups or {})
        else:
            self._start_threads(max_concurrency)

    # ---------------------------------------------------------- sync actors
    def _start_threads(self, n: int) -> None:
        for i in range(max(1, n)):
            self._threads.spawn(
                self._thread_main,
                f"actor-{self.actor_id.hex()[:6]}-{i}")

    def _thread_main(self) -> None:
        while True:
            with self._cv:
                while not self._runnable_locked():
                    if self.dead:
                        return
                    # periodic wake (RC17): the loop re-checks
                    # dead/runnable, so a lost notify costs one wake
                    # period instead of a wedged executor thread
                    self._cv.wait(
                        Config.instance().actor_executor_wake_s)
                call = heapq.heappop(self._heap)
                if self.max_concurrency == 1:
                    self._next_seq = call.seq_no + 1
                self._inflight += 1
            try:
                call.execute()
            finally:
                # no notify: workers only wait for heap items, and
                # completion never makes a queued item newly runnable
                # (for max_concurrency==1, _next_seq advanced at pop)
                with self._lock:
                    self._inflight -= 1

    def _runnable_locked(self) -> bool:
        if not self._heap:
            return False
        if self.max_concurrency == 1 and not self.execute_out_of_order:
            # strict sequence order (sequential_actor_submit_queue.cc)
            return self._heap[0].seq_no <= self._next_seq
        # out-of-order (or concurrent): anything queued is dispatchable
        # (out_of_order_actor_scheduling_queue.cc)
        return True

    # --------------------------------------------------------- async actors
    def _start_async_loop(self, concurrency_groups: Dict[str, int]) -> None:
        started = threading.Event()

        def _loop_main():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            self._sem = asyncio.Semaphore(self.max_concurrency)
            for name, limit in concurrency_groups.items():
                self._group_sems[name] = asyncio.Semaphore(limit)
            started.set()
            loop.run_forever()
            # drain cancelled tasks on exit
            pending = asyncio.all_tasks(loop)
            for task in pending:
                task.cancel()
            if pending:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True))
            loop.close()

        self._threads.spawn(
            _loop_main, f"actor-{self.actor_id.hex()[:6]}-loop")
        started.wait()

    # ------------------------------------------------------------ submission
    def submit(self, seq_no: int, method_name: str, execute: Callable[[], None],
               fail: Optional[Callable[[], None]] = None,
               concurrency_group: str = "") -> None:
        if self.dead:
            if fail is not None:
                fail()
            return
        if self.is_async:
            sem = self._group_sems.get(concurrency_group, self._sem)
            with self._lock:
                self._async_pending += 1

            async def _run():
                try:
                    async with sem:
                        if self.dead:
                            if fail is not None:
                                fail()
                            return
                        result = execute()
                        if inspect.isawaitable(result):
                            await result
                finally:
                    with self._lock:
                        self._async_pending -= 1

            def _schedule():
                asyncio.ensure_future(_run())

            self._loop.call_soon_threadsafe(_schedule)
        else:
            with self._cv:
                if self.dead:
                    call_fail = fail
                else:
                    heapq.heappush(
                        self._heap,
                        _QueuedCall(seq_no=seq_no, method_name=method_name,
                                    execute=execute, fail=fail),
                    )
                    self._cv.notify_all()
                    call_fail = None
            if call_fail is not None:
                call_fail()

    def pending_count(self) -> int:
        with self._lock:
            return len(self._heap) + self._inflight + self._async_pending

    # -------------------------------------------------------------- shutdown
    def kill(self) -> None:
        with self._cv:
            self.dead = True
            dropped = list(self._heap)
            self._heap.clear()
            self._cv.notify_all()
        # process-backed actors: terminate the dedicated worker process
        on_kill = getattr(self.instance, "__ray_on_kill__", None)
        if on_kill is not None:
            try:
                on_kill()
            except Exception:
                logger.exception("error terminating actor worker process")
        for call in dropped:
            if call.fail is not None:
                try:
                    call.fail()
                except Exception:
                    logger.exception("error failing dropped actor call")
        if self.is_async and self._loop is not None:
            try:
                self._loop.call_soon_threadsafe(self._loop.stop)
            except RuntimeError as e:
                # loop already closed by a prior kill
                logger.debug("async actor loop stop raced a prior "
                             "kill: %r", e)
        # executor threads saw `dead` (or the loop stop): join them by
        # name under a short budget — a method call wedged past death
        # surfaces as a WARN instead of a leaked thread
        self._threads.join_all(timeout=1.0)


@dataclass
class ActorRecord:
    actor_id: ActorID
    state: ActorState
    creation_spec: Any                      # ActorCreationSpec
    node_id: Optional[NodeID] = None
    executor: Optional[ActorExecutor] = None
    name: Optional[str] = None
    namespace: str = ""
    detached: bool = False
    restarts_remaining: int = 0
    num_restarts: int = 0
    death_cause: str = ""
    # calls buffered while pending/restarting: (submit_fn)
    buffered_calls: List[Callable[[], None]] = field(default_factory=list)
    seq_counter: int = 0
    lock: threading.Lock = field(default_factory=threading.Lock)

    def next_seq(self) -> int:
        with self.lock:
            seq = self.seq_counter
            self.seq_counter += 1
            return seq


class ActorDirectory:
    """GCS-side actor bookkeeping: FSM + named-actor registry
    (reference: gcs/gcs_server/gcs_actor_manager.cc)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._actors: Dict[ActorID, ActorRecord] = {}
        self._named: Dict[Tuple[str, str], ActorID] = {}

    def register(self, record: ActorRecord) -> None:
        with self._lock:
            if record.name:
                key = (record.namespace, record.name)
                existing = self._named.get(key)
                if existing is not None:
                    rec = self._actors.get(existing)
                    if rec is not None and rec.state is not ActorState.DEAD:
                        raise ValueError(
                            f"Actor name {record.name!r} already taken in "
                            f"namespace {record.namespace!r}")
                self._named[key] = record.actor_id
            self._actors[record.actor_id] = record

    def get(self, actor_id: ActorID) -> Optional[ActorRecord]:
        with self._lock:
            return self._actors.get(actor_id)

    def get_by_name(self, name: str, namespace: str) -> Optional[ActorRecord]:
        with self._lock:
            aid = self._named.get((namespace, name))
            return self._actors.get(aid) if aid else None

    def set_state(self, actor_id: ActorID, state: ActorState) -> None:
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec:
                rec.state = state

    def mark_dead(self, actor_id: ActorID, cause: str = "") -> None:
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec:
                rec.state = ActorState.DEAD
                rec.death_cause = cause
                if rec.name:
                    self._named.pop((rec.namespace, rec.name), None)

    def list(self) -> List[ActorRecord]:
        with self._lock:
            return list(self._actors.values())

    def flush_buffered(self, actor_id: ActorID) -> None:
        with self._lock:
            rec = self._actors.get(actor_id)
            if not rec:
                return
            calls, rec.buffered_calls = rec.buffered_calls, []
        for call in calls:
            call()
