"""Per-node scheduling and dispatch — the raylet.

Re-implements the reference raylet's scheduling pipeline
(src/ray/raylet/node_manager.h, cluster_task_manager.h:111-125):

  submit -> [schedule: pick node over cluster matrix] -> local? queue for
  dispatch -> [resolve arg dependencies] -> [allocate resources]
  -> run on a worker | remote? forward (spillback) | nowhere? infeasible

Differences from the reference, by design:
  - Scheduling is *batched*: each tick drains the pending queue, groups
    tasks by SchedulingClass, and runs one vectorized placement solve over
    the dense [nodes x resources] matrix (BatchedHybridPolicy) instead of
    an O(nodes) scan per task.
  - In-process mode workers are threads with stable WorkerIDs; the
    multiprocess runtime swaps in OS-process workers behind the same
    WorkerPool interface (reference: worker_pool.h:144).

All cluster state a raylet needs is injected (ClusterState), mirroring the
reference's callback-injected ClusterTaskManager (cluster_task_manager.h:
127-145) so the whole pipeline is unit-testable with synthetic state.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import defaultdict, deque
from operator import attrgetter

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set

import numpy as np

from ray_tpu._private.config import Config
from ray_tpu._private.ids import NodeID, TaskID, WorkerID
from ray_tpu.core.task_spec import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    TaskSpec,
)
from ray_tpu.scheduler.policy import (
    BatchedHybridPolicy,
    DeviceMatrixMirror,
    HybridPolicy,
    SchedulingOptions,
    device_solve_available,
    shared_batched_policy,
)
from ray_tpu.scheduler.resources import (
    NodeResources,
    ResourceMatrix,
    ResourceRequest,
    StringIdMap,
)

logger = logging.getLogger(__name__)

# dispatch fast lane: C-level accessor for the bulk-dispatch hot loop
# (any(map(...)) over this beats a Python-level genexpr pass)
_GET_CANCELLED = attrgetter("cancelled")


class _TickRateLimiter:
    """Per-raylet sampling gate for tick anatomy.

    Replaces the old ``_TickPhases._last_start`` class global, which was
    read and written unsynchronized from every scheduling thread AND
    shared between unrelated Raylet instances — in an in-process
    cluster one chatty raylet could starve every other raylet's anatomy
    for the whole interval. One limiter per Raylet, one lock per
    decision; a fresh raylet's first tick is always instrumented."""

    __slots__ = ("_lock", "_last")

    def __init__(self):
        self._lock = threading.Lock()
        self._last = 0.0

    def try_acquire(self, now: float, min_interval: float) -> bool:
        # Lock-free fast reject: `_last` is a monotonically increasing
        # float, so a torn/stale read can only UNDER-estimate it — the
        # worst case is falling through to the locked re-check, never a
        # wrongly suppressed sample. A micro-tick storm (the submit hot
        # path: one task per tick) pays a clock read + compare here and
        # skips the lock entirely between samples.
        if now - self._last < min_interval:
            return False
        with self._lock:
            if now - self._last < min_interval:
                return False
            self._last = now
            return True

    def reset(self) -> None:
        """Forget the last instrumented tick (bench/tests defeat the
        rate limit deterministically through this)."""
        with self._lock:
            self._last = 0.0


class _TickPhases:
    """Named-phase timer for one scheduling tick (observability plane).

    Phase semantics: collect (drain pending under the raylet lock) |
    refresh (fold matrix deltas, incl. the device-mirror sync) | solve
    (host solve, or time BLOCKED pulling a device result) | overlap
    (host commit/placement work done while a device solve is still in
    flight — the pipelined tick's win shows up here) | commit
    (placement bookkeeping with no solve in flight, incl. the per-task
    scan for strategy tasks and the single-node fast path) | spillback
    (remote re-submits) | dispatch (worker fan-out). Marks are
    monotonic deltas and ACCUMULATE per phase, so the pipelined drain
    loop's repeated passes still report disjoint, truthful sums;
    flush() feeds the scheduler_phase_ms histogram and, when a sampled
    trace is active, a per-tick span tree — which is how BENCH prints
    where the tick wall time goes (ROADMAP Open item 2: the
    80 k/s-vs-3.4 M gap lives between the solves).

    Cost control: instrumented ticks are rate-limited to one per
    ``MIN_INTERVAL_S`` per raylet (via its :class:`_TickRateLimiter`) —
    a storm of micro-ticks (one task each, the submit hot path) pays
    only a clock read + lock + compare per tick, while any tick that
    runs longer than the interval is always captured (the window has
    necessarily elapsed by the time the next tick constructs its
    timer). Zero-cost when the plane is off: one bool check per mark.
    """

    __slots__ = ("enabled", "phases", "_t", "wall_start")

    PHASES = ("collect", "refresh", "solve", "overlap", "commit",
              "spillback", "dispatch")
    MIN_INTERVAL_S = 0.01

    def __init__(self, enabled: bool,
                 limiter: Optional[_TickRateLimiter] = None):
        self.phases: Dict[str, float] = {}
        if enabled:
            now = time.monotonic()
            if limiter is not None and not limiter.try_acquire(
                    now, self.MIN_INTERVAL_S):
                enabled = False  # anatomy sampled out for this tick
            else:
                self._t = now
                # raycheck: disable=RC02 — wall-clock span timestamp for trace correlation, not deadline arithmetic
                self.wall_start = time.time()
        self.enabled = enabled
        if not enabled:
            self._t = 0.0
            self.wall_start = 0.0

    def mark(self, phase: str) -> None:
        if not self.enabled:
            return
        now = time.monotonic()
        self.phases[phase] = self.phases.get(phase, 0.0) \
            + (now - self._t)
        self._t = now

    def flush(self) -> None:
        if not self.enabled or not self.phases:
            return
        try:
            from ray_tpu.observability.metrics import scheduler_phase_ms

            for phase, dt in self.phases.items():
                scheduler_phase_ms.observe(dt * 1e3,
                                           tags={"phase": phase})
        except Exception as e:
            logger.debug("tick phase metrics failed: %r", e)
        from ray_tpu.util import tracing

        if tracing.enabled():
            tracing.record_span_tree(
                "scheduler.tick", self.wall_start,
                [(f"scheduler.tick.{p}", self.phases[p])
                 for p in self.PHASES if p in self.phases],
                attributes={f"{p}_ms": round(dt * 1e3, 3)
                            for p, dt in self.phases.items()})


class ClusterState:
    """Shared cluster resource view: the dense matrix + raylet registry.

    In-process this is literally shared; in multiprocess mode each node
    holds a replica kept fresh by the GCS resource broadcast (reference:
    gcs_resource_manager.cc + grpc_based_resource_broadcaster.cc).
    """

    def __init__(self):
        self.ids = StringIdMap()
        self.matrix = ResourceMatrix(self.ids)
        self.raylets: Dict[NodeID, "Raylet"] = {}
        self.lock = threading.RLock()
        # topology epoch: bumped on every node death/removal, read by
        # the pipelined tick's fencing check (Config.tick_epoch_fencing)
        # — a device solve launched under epoch E commits only if the
        # topology is still E; otherwise its counts were computed
        # against a matrix with a dead node in it and are re-solved on
        # host. Guarded by ``lock``.
        self.epoch = 0
        # invoked whenever a node frees resources (PG retries hook here)
        self.freed_callbacks: List[Callable[[], None]] = []
        # raylets whose local_resources changed since the matrix was last
        # refreshed; rows are folded in lazily at the next read (the
        # resource-report batching of gcs_resource_report_poller.cc, in
        # lazy form) so the per-task dispatch/finish path stays O(1)
        self._dirty: set = set()
        # lazy device-resident mirror of `matrix` — only pipelined
        # device ticks pay for it (one per cluster: the matrix it
        # shadows is cluster-wide, and its jit caches are shared)
        self.device_mirror: Optional[DeviceMatrixMirror] = None

    def device_mirror_locked(self) -> DeviceMatrixMirror:
        """The cluster's device matrix mirror. Caller holds ``lock``."""
        if self.device_mirror is None:
            self.device_mirror = DeviceMatrixMirror()
        return self.device_mirror

    def notify_freed(self) -> None:
        for cb in list(self.freed_callbacks):
            try:
                cb()
            except Exception:
                logger.exception("resource-freed callback failed")

    def register(self, raylet: "Raylet") -> None:
        with self.lock:
            self.raylets[raylet.node_id] = raylet
            self.matrix.upsert(raylet.node_id, raylet.local_resources)

    def unregister(self, node_id: NodeID) -> None:
        with self.lock:
            self.raylets.pop(node_id, None)
            self.matrix.set_alive(node_id, False)
            self.epoch += 1  # fences any in-flight pipelined solve

    def set_draining(self, node_id: NodeID) -> None:
        """Drain plane: exclude NODE from every placement solve via the
        matrix alive mask (the same row every tick, spillback, and PG
        pack reads) while the raylet itself keeps running — queued and
        running work finishes or spills; nothing new lands. The epoch
        bump fences in-flight pipelined device solves exactly like
        unregister, so a double-buffered batch solved against the
        pre-drain mask is discarded instead of committed."""
        with self.lock:
            if node_id not in self.raylets:
                return
            self.matrix.set_alive(node_id, False)
            self.epoch += 1

    def sync(self, raylet: "Raylet") -> None:
        """Mark a raylet's matrix row stale; folded in by refresh_locked
        at the next scheduling read."""
        with self.lock:
            self._dirty.add(raylet)

    def refresh_locked(self) -> None:
        """Fold pending resource changes into the dense matrix. Caller
        must hold ``self.lock``."""
        if self._dirty:
            for raylet in self._dirty:
                if raylet.node_id in self.raylets:
                    self.matrix.upsert(raylet.node_id,
                                       raylet.local_resources)
            self._dirty.clear()

    def alive_raylets(self) -> List["Raylet"]:
        with self.lock:
            self.refresh_locked()
            return [
                r for r in self.raylets.values()
                if self.matrix.alive[self.matrix.slot_of(r.node_id)]
            ]


@dataclass(eq=False)
class _PendingTask:
    # eq=False keeps object-identity hashing, so the raylet's running
    # set can hold the tasks themselves and register a whole dispatch
    # grant with one C-level set.update — a TaskID-keyed dict paid a
    # Python-level __hash__ call per insert on the hottest tick path
    spec: TaskSpec
    on_dispatch: Callable[["Raylet", WorkerID], None]
    spillback_count: int = 0
    cancelled: bool = False


class WorkerPool:
    """Thread-backed worker pool with stable worker identities.

    PopWorker/PushWorker shaped like the reference (worker_pool.h:74) but
    leases are implicit: dispatch just runs on a pool thread and the
    executing thread adopts a WorkerID. Work travels through a C-level
    SimpleQueue — cheaper per task than ThreadPoolExecutor, which builds
    a Future (with its Condition) per submit on the hottest path.
    Threads spawn on demand up to max_workers, like the reference's
    worker-pool prestart-on-demand."""

    def __init__(self, node_id: NodeID, max_workers: int = 256):
        import queue

        from ray_tpu.cluster.threads import ThreadRegistry

        self.node_id = node_id
        self.max_workers = max_workers
        # raycheck: disable=RC10 — admission happens upstream: an item only enqueues after local_resources.allocate() succeeded, so depth is bounded by the node's resource capacity
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._num_started = 0
        self._num_threads = 0
        self._idle = 0
        self._claimed = 0  # idle slots pre-claimed by in-flight submits
        self._shutdown = False
        self._name_prefix = f"worker-{node_id.hex()[:6]}"
        # worker threads spawn through the registry so shutdown() can
        # join them by name and surface a hung task (raycheck RC09)
        self._threads = ThreadRegistry(self._name_prefix)

    def current_worker_id(self) -> WorkerID:
        wid = getattr(self._tls, "worker_id", None)
        if wid is None:
            wid = WorkerID.from_random()
            self._tls.worker_id = wid
            with self._lock:
                self._num_started += 1
        return wid

    def submit(self, fn: Callable, *args) -> bool:
        """False when the pool is already shut down (node died)."""
        if self._shutdown:
            return False
        # Reserve an idle worker for this item ATOMICALLY, or spawn a new
        # thread. Two concurrent submits must not both claim one idle
        # worker and neither spawn (ThreadPoolExecutor reserves via its
        # idle semaphore; this lock plays that role).
        with self._lock:
            if self._shutdown:
                return False
            if self._idle > 0:
                self._idle -= 1  # claimed; the dequeuing worker skips its
                #                  own decrement via _claimed
                self._claimed += 1
            elif self._num_threads < self.max_workers:
                self._num_threads += 1
                self._threads.spawn(
                    self._worker_loop,
                    f"{self._name_prefix}-{self._num_threads}")
        self._queue.put((fn, args))
        return True

    def submit_batch(self, items: List[tuple]) -> bool:
        """Batched submit (the dispatch fast lane's worker fan-out):
        claim idle workers and spawn threads for the WHOLE group under
        one lock acquisition, then enqueue every item — instead of one
        lock round trip per task. ``items`` are ``(fn, args)`` tuples,
        exactly what :meth:`_worker_loop` dequeues. False when the pool
        is already shut down (node died) — no item was enqueued."""
        if self._shutdown:
            return False
        n = len(items)
        if not n:
            return True
        with self._lock:
            if self._shutdown:
                return False
            claim = self._idle if self._idle < n else n
            if claim:
                self._idle -= claim
                self._claimed += claim
            spawn = n - claim
            if spawn > self.max_workers - self._num_threads:
                spawn = self.max_workers - self._num_threads
            for _ in range(spawn):
                self._num_threads += 1
                self._threads.spawn(
                    self._worker_loop,
                    f"{self._name_prefix}-{self._num_threads}")
        put = self._queue.put
        for item in items:
            put(item)
        return True

    def _worker_loop(self) -> None:
        self.current_worker_id()
        while True:
            with self._lock:
                self._idle += 1
            item = self._queue.get()
            with self._lock:
                if self._claimed > 0:
                    # a submit already decremented _idle on our behalf
                    self._claimed -= 1
                else:
                    self._idle -= 1
            if item is None or self._shutdown:
                return
            fn, args = item
            try:
                fn(*args)
            except Exception:
                logger.exception("uncaught error in worker task")

    def shutdown(self) -> None:
        self._shutdown = True
        with self._lock:
            for _ in range(self._num_threads):
                self._queue.put(None)
        # sentinels unblock every worker; join them by name so a task
        # wedged past shutdown is WARN-logged instead of leaking (a
        # short budget: in-process shutdown must stay snappy)
        self._threads.join_all(timeout=0.5)

    @property
    def num_started(self) -> int:
        return self._num_started


class DependencyManager:
    """Waits for a task's ObjectRef arguments to be locally available
    (reference: raylet/dependency_manager.h:49 driving the PullManager)."""

    def __init__(self, object_store):
        self._store = object_store

    def wait_ready(self, spec: TaskSpec, callback: Callable[[], None]) -> None:
        if not spec.args and not spec.kwargs:  # hot path: no deps at all
            callback()
            return
        from ray_tpu.core.object_ref import ObjectRef

        deps = [a.id() for a in spec.args if isinstance(a, ObjectRef)]
        deps += [v.id() for v in spec.kwargs.values() if isinstance(v, ObjectRef)]
        if not deps:
            callback()
            return
        remaining = len(deps)
        lock = threading.Lock()

        def _one_ready():
            nonlocal remaining
            with lock:
                remaining -= 1
                done = remaining == 0
            if done:
                # spilled args restore under TASK_ARGS admission — below
                # get/wait requests in the pull manager's priority order
                # (reference: DependencyManager drives the PullManager
                # with TASK_ARGS bundles)
                from ray_tpu.exceptions import ObjectCorruptedError
                from ray_tpu.scheduler.pull_manager import BundlePriority

                try:
                    self._store.restore_spilled(
                        deps, priority=BundlePriority.TASK_ARGS)
                except ObjectCorruptedError as e:
                    # a spilled arg failed its digest and dropped
                    # itself (integrity plane). This callback runs on
                    # the PUTTING thread, so recovery can't block
                    # here: proceed — the task's own arg resolution
                    # surfaces the miss, and ray.get-driven lineage
                    # reconstruction recovers the object
                    logger.warning("task arg corrupt at restore: %s", e)
                callback()

        for oid in deps:
            self._store.on_available(oid, _one_ready)

    def wait_ready_batch(self, tasks: List["_PendingTask"],
                         ready_cb: Callable[[List["_PendingTask"]], None],
                         one_cb: Callable[["_PendingTask"], None]) -> None:
        """Batched readiness check (dispatch fast lane). Tasks with no
        arguments at all — the hot case; there is nothing to wait for —
        are collected and handed to ``ready_cb`` in ONE call so the
        caller can fan them out to workers as a group. Everything else
        takes the exact per-task :meth:`wait_ready` path with
        ``one_cb`` (per-dependency callbacks cannot batch: each task
        becomes ready at its own time)."""
        ready: List["_PendingTask"] = []
        for task in tasks:
            spec = task.spec
            if not spec.args and not spec.kwargs:
                ready.append(task)
            else:
                self.wait_ready(spec, lambda t=task: one_cb(t))
        if ready:
            ready_cb(ready)


class Raylet:
    def __init__(
        self,
        node_id: NodeID,
        resources: Dict[str, float],
        cluster: ClusterState,
        dependency_manager: DependencyManager,
        labels: Optional[Dict[str, str]] = None,
        max_workers: int = 256,
    ):
        self.node_id = node_id
        self.cluster = cluster
        self.local_resources = NodeResources.from_map(resources, cluster.ids)
        if labels:
            self.local_resources.labels.update(labels)
        self.worker_pool = WorkerPool(node_id, max_workers=max_workers)
        self.deps = dependency_manager
        self._lock = threading.RLock()
        # pending placement decisions, FIFO within scheduling class
        # raycheck: disable=RC10 — bounded by the submit() admission check (raylet_max_queued_tasks): over-bound fresh submits are pushed back with RetryLaterError
        self._pending: deque[_PendingTask] = deque()
        # placed locally, waiting for deps+resources; one FIFO queue per
        # resource-demand key so a dispatch tick is O(demand shapes), not
        # O(tasks) (reference: per-SchedulingClass lease queues in
        # cluster_task_manager.cc:295)
        self._dispatch_queues: Dict[tuple, deque] = {}
        self._dispatch_len = 0
        self._infeasible: List[_PendingTask] = []
        self._by_task_id: Dict[TaskID, _PendingTask] = {}
        # running tasks by identity — finish_task recovers the grant to
        # free from the spec's memoized resource_request (warm for every
        # task by submit time), so dispatch writes nothing per task
        self._running_tasks: Set[_PendingTask] = set()
        # PG 2PC bundle states ("prepared"|"committed") keyed by
        # (pg_id, bundle_index) — prepare/commit/return are idempotent,
        # mirroring the process tier's contract (raylet_server.py)
        self._pg_bundles: Dict[tuple, str] = {}
        self.policy = HybridPolicy()
        # numpy water-filling: at in-process matrix sizes the device
        # round-trip of the jit path costs more than it saves; the jit
        # variant is exercised by bench.py over 100k-task matrices.
        self.batched_policy = BatchedHybridPolicy(use_jax=False)
        self._spread_rr = 0  # round-robin cursor for SPREAD strategy
        self._tick_limiter = _TickRateLimiter()
        self.num_scheduled = 0
        self.num_spilled_back = 0
        self.dead = False

    @property
    def _running(self) -> Dict[TaskID, ResourceRequest]:
        """Monitoring/test view of the running set, keyed by TaskID
        like the dict it replaced (load_metrics truthiness, test-suite
        iteration). Built on demand — callers hold ``_lock``; the hot
        paths only touch ``_running_tasks``."""
        return {t.spec.task_id: t.spec.resource_request(self.cluster.ids)
                for t in tuple(self._running_tasks)}

    # ------------------------------------------------------------------ API
    def submit(self, spec: TaskSpec,
               on_dispatch: Callable[["Raylet", WorkerID], None],
               spillback_count: int = 0) -> None:
        """QueueAndScheduleTask (reference cluster_task_manager.cc:500).

        Fresh submits (spillback_count == 0) pass an admission check: a
        backlog at or over ``raylet_max_queued_tasks`` raises
        :class:`~ray_tpu.exceptions.RetryLaterError` so Runtime.submit
        slows the producer down instead of the queues growing without
        bound. Spillbacks are exempt — they already hold a placement
        decision, and bouncing them mid-schedule_tick would lose work.
        """
        task = _PendingTask(spec, on_dispatch, spillback_count)
        if spillback_count == 0:
            from ray_tpu.observability.metrics import tasks_submitted

            cfg = Config.instance()
            if cfg.overload_enabled:
                with self._lock:
                    backlog = len(self._pending) + self._dispatch_len
                if backlog >= cfg.raylet_max_queued_tasks:
                    from ray_tpu.exceptions import RetryLaterError
                    from ray_tpu.observability.metrics import tasks_shed

                    tasks_shed.inc()
                    raise RetryLaterError(
                        f"raylet {self.node_id.hex()[:8]} backlog is "
                        f"full ({backlog} queued); slow down",
                        retry_after_s=min(2.0, 0.02 + 1e-4 * backlog))
            tasks_submitted.inc()
            # FAST PATH — the lease-reuse analogue (reference: tasks with
            # a known SchedulingKey pipeline onto an already-leased local
            # worker, direct_task_transport.cc:150 OnWorkerIdle): a plain
            # task with no backlog and local capacity skips the placement
            # solve and dispatches immediately.
            if (spec.scheduling_strategy is None
                    and not self._pending and not self._dispatch_len):
                req = spec.resource_request(self.cluster.ids)
                with self._lock:
                    if self.local_resources.allocate(req):
                        self._running_tasks.add(task)
                        self._by_task_id[spec.task_id] = task
                        self.num_scheduled += 1
                        dispatched = True
                    else:
                        dispatched = False
                if dispatched:
                    self.cluster.sync(self)
                    self.deps.wait_ready(
                        spec, lambda t=task: self._run_task(t))
                    return
        with self._lock:
            self._pending.append(task)
            self._by_task_id[spec.task_id] = task
        self.schedule_tick()

    def submit_batch(self, tasks: List[_PendingTask]) -> None:
        """Spillback fan-in: accept a whole batch of already-placed
        tasks from a peer raylet in ONE frame — one lock acquisition
        and one scheduling tick for the group, instead of the per-task
        submit()/tick cycle the old spillback loop paid. Spillbacks are
        admission-exempt exactly as in :meth:`submit`: they already
        hold a placement decision and bouncing them would lose work."""
        if not tasks:
            return
        with self._lock:
            for task in tasks:
                self._pending.append(task)
                self._by_task_id[task.spec.task_id] = task
        self.schedule_tick()

    def cancel(self, task_id: TaskID) -> bool:
        with self._lock:
            task = self._by_task_id.get(task_id)
            if task is None:
                return False
            task.cancelled = True
            return True

    # ------------------------------------------------------- scheduling tick
    def schedule_tick(self) -> None:
        """Drain the pending queue through batched placement solves.

        Two implementations behind the ``scheduler_pipeline_enabled``
        master switch:

        - OFF: :meth:`_schedule_tick_single`, the exact single-buffered
          tick (one batch, solve blocks inside the cluster lock, the
          per-task commit walk) — bit-for-bit the pre-pipeline path.
        - ON: :meth:`_schedule_tick_pipelined`, the drain loop that
          double-buffers device solves against host commit work,
          solves against the cluster's device-resident matrix mirror,
          and commits/spills in vectorized batches.

        Observability plane: either tick is split into the named phases
        of :class:`_TickPhases` (collect → refresh → solve → overlap →
        commit → spillback → dispatch), observed into the
        ``scheduler_phase_ms`` histogram per tick so bench/status
        readouts can pin which phase the tick wall time goes to."""
        from ray_tpu.cluster import overload as _overload
        from ray_tpu.observability.metrics import scheduler_ticks

        scheduler_ticks.inc()
        cfg = Config.instance()
        # lane_enabled = the master switch AND'd with the scheduler
        # lane breaker: K consecutive fenced/failed pipelined ticks
        # degrade to the single-buffered tick until a half-open probe
        # tick survives (Config.fastlane_breaker_*)
        if _overload.lane_enabled("scheduler"):
            try:
                fenced = self._schedule_tick_pipelined(cfg)
            except BaseException:
                _overload.lane_failed("scheduler")
                raise
            if fenced:
                _overload.lane_failed("scheduler")
            else:
                _overload.lane_ok("scheduler")
        else:
            self._schedule_tick_single(cfg)

    def _schedule_tick_single(self, cfg: Config) -> None:
        """The single-buffered tick: one batch per call, the device
        solve (if any) pulled synchronously, per-task commit. Kept
        verbatim as the ``scheduler_pipeline_enabled=False`` reference
        semantics — same placements for the same seed as every release
        before the pipeline landed."""
        ph = _TickPhases(cfg.observability_plane_enabled,
                         self._tick_limiter)
        with self._lock:
            if not self._pending:
                self._dispatch_tick()
                return
            batch: List[_PendingTask] = []
            while self._pending and len(batch) < cfg.scheduler_max_tasks_per_tick:
                batch.append(self._pending.popleft())
        ph.mark("collect")
        placed_remote: List[tuple[_PendingTask, "Raylet"]] = []
        with self.cluster.lock:
            self.cluster.refresh_locked()
            ph.mark("refresh")
            matrix = self.cluster.matrix
            local_slot = matrix.slot_of(self.node_id)
            # Single-alive-node fast path: every placement answer is
            # "here" (or infeasible) — skip the policy solve entirely.
            # NodeAffinity to a *missing* node is the one strategy that
            # can still answer differently; route those to the slow path.
            if (local_slot is not None
                    and int(matrix.alive.sum()) == 1
                    and bool(matrix.alive[local_slot])):
                for task in batch:
                    if task.cancelled:
                        self._finish_cancelled(task)
                        continue
                    strategy = task.spec.scheduling_strategy
                    if isinstance(strategy, NodeAffinitySchedulingStrategy):
                        slot = self._schedule_one_locked(
                            task, matrix, local_slot)
                    else:
                        req = task.spec.resource_request(self.cluster.ids)
                        slot = (local_slot
                                if self.local_resources.is_feasible(req)
                                else None)
                    if slot is None:
                        self._mark_infeasible(task)
                        continue
                    self._commit_placement(task, slot, matrix, placed_remote)
                batch = []
            # Partition: plain tasks batch through the vectorized solve,
            # strategy/spillback-constrained ones take the per-task scan.
            per_class: Dict[int, List[_PendingTask]] = defaultdict(list)
            singles: List[_PendingTask] = []
            for task in batch:
                if task.cancelled:
                    self._finish_cancelled(task)
                elif (task.spec.scheduling_strategy is None
                      and task.spillback_count == 0):
                    per_class[task.spec.scheduling_class].append(task)
                else:
                    singles.append(task)
            threshold = cfg.scheduler_batch_threshold
            big_classes: List[List[_PendingTask]] = []
            for tasks in per_class.values():
                if len(tasks) < threshold:
                    singles.extend(tasks)
                else:
                    big_classes.append(tasks)
            if big_classes:
                reqs = np.stack([
                    tasks[0].spec.resource_request(self.cluster.ids)
                    .dense(matrix.width) for tasks in big_classes])
                ks = np.array([len(tasks) for tasks in big_classes],
                              dtype=np.int64)
                opts = SchedulingOptions.default()
                cells = matrix.total.shape[0] * len(big_classes)
                if (cfg.scheduler_use_vectorized_policy
                        and cfg.scheduler_device_solve_min_cells >= 0
                        and cells >= cfg.scheduler_device_solve_min_cells
                        and device_solve_available()):
                    # Device path on the LIVE tier: one fused jit solve
                    # for the whole tick, then the exact int64 repair —
                    # the same kernel bench.py drains 100k tasks through
                    # (north-star: scheduling_policy.cc:150 replaced
                    # behind the ISchedulingPolicy-shaped seam).
                    dev = shared_batched_policy(use_jax=True)
                    counts_dev = dev.schedule_tick_fused(
                        reqs, ks, matrix.total, matrix.available,
                        matrix.alive, local_slot, opts)
                    counts = dev.repair_oversubscription(
                        reqs, np.asarray(counts_dev), matrix.available)
                else:
                    counts = self.batched_policy.schedule_classes(
                        reqs, ks, matrix.total, matrix.available,
                        matrix.alive, local_slot, opts)
                ph.mark("solve")
                for tasks, row in zip(big_classes, counts):
                    it = iter(tasks)
                    for slot in np.flatnonzero(row):
                        for _ in range(int(row[slot])):
                            self._commit_placement(
                                next(it), int(slot), matrix, placed_remote)
                    # capacity-exhausted leftovers: feasible-but-
                    # unavailable nodes are still legal targets (they
                    # queue for dispatch)
                    singles.extend(it)
            for task in singles:
                slot = self._schedule_one_locked(task, matrix, local_slot)
                if slot is None:
                    self._mark_infeasible(task)
                    continue
                self._commit_placement(task, slot, matrix, placed_remote)
            ph.mark("commit")
        for task, raylet in placed_remote:
            self.num_spilled_back += 1
            with self._lock:
                self._by_task_id.pop(task.spec.task_id, None)
            raylet.submit(task.spec, task.on_dispatch,
                          spillback_count=task.spillback_count + 1)
        ph.mark("spillback")
        self._dispatch_tick()
        ph.mark("dispatch")
        ph.flush()

    # drain-loop runaway guard: leftovers past this many batches stay
    # queued for the next tick call (the old path's one-batch-per-call
    # bound, relaxed enough for the 100k drain to finish in one call)
    _MAX_PIPELINE_BATCHES = 4096

    def _schedule_tick_pipelined(self, cfg: Config) -> bool:
        """Pipelined drain loop (ROADMAP Open item 2). Per iteration::

          host:   collect_i·refresh_i·dispatch-solve_i·singles_i | commit_{i-1}·spill_{i-1}·dispatch_{i-1}
          device:  ...___solve_{i-1}___________________________/ \\___solve_i___...

        (a) Double-buffered solves: the fused device solve for batch i
        is DISPATCHED asynchronously under the cluster lock (jax async
        dispatch returns without blocking) and its counts are pulled
        one iteration later, OUTSIDE every lock, after the host has
        finished committing batch i-1 — solve and commit wall time
        overlap instead of summing. (b) The solve reads the cluster's
        :class:`~ray_tpu.scheduler.policy.DeviceMatrixMirror` (dirty-
        row delta uploads into donated device buffers) instead of
        re-coercing and re-uploading the full matrix every batch.
        (c) Commit and spillback fan out vectorized (_commit_counts /
        _spillback_batched).

        Soundness: a pipelined solve is stale by at most the previous
        batch's dispatch allocations, so its counts pass
        ``repair_oversubscription`` against the CURRENT exact int64
        host availability before committing — a stale solve can only
        under-place (leftovers re-route through the per-task path),
        and allocation itself stays exact at dispatch time (placement
        is a queueing decision, not an allocation). The OFF switch
        (``scheduler_pipeline_enabled=False``) reproduces the old
        single-buffered tick bit-for-bit.

        Epoch fencing (``tick_epoch_fencing``): each dispatched solve
        carries the cluster topology epoch it was launched under; a
        node death between launch and commit bumps the epoch, and the
        commit discards the stale device counts and re-solves on host
        against the repaired matrix. Returns True when any batch in
        this tick was fenced (the scheduler lane breaker's failure
        signal)."""
        ph = _TickPhases(cfg.observability_plane_enabled,
                         self._tick_limiter)
        opts = SchedulingOptions.default()
        inflight = None  # prev batch's (big_classes, reqs, counts_dev, epoch)
        fenced = False
        batches = 0
        while batches < self._MAX_PIPELINE_BATCHES:
            with self._lock:
                batch: List[_PendingTask] = []
                while (self._pending
                       and len(batch) < cfg.scheduler_max_tasks_per_tick):
                    batch.append(self._pending.popleft())
            ph.mark("collect")
            if not batch and inflight is None:
                break
            batches += 1
            placed_remote: List[tuple] = []
            solve_ctx = None
            if batch:
                solve_ctx, placed_remote = self._pipeline_front_half(
                    cfg, opts, batch, ph)
            if placed_remote:
                self._spillback_batched(placed_remote)
                ph.mark("spillback")
            if inflight is not None:
                # OVERLAP: the device is (possibly) solving THIS batch
                # while the host repairs/commits the PREVIOUS one
                fenced |= self._finish_device_batch(
                    inflight, ph, cfg, solving=solve_ctx is not None)
            inflight = solve_ctx
            self._dispatch_tick()
            ph.mark("dispatch")
        if batches == 0:
            self._dispatch_tick()
            ph.mark("dispatch")
        ph.flush()
        return fenced

    def _pipeline_front_half(self, cfg: Config, opts: SchedulingOptions,
                             batch: List[_PendingTask], ph: _TickPhases):
        """Collect-side half of one drain iteration: refresh cluster
        state, DISPATCH (not pull) the device solve for this batch, and
        place everything needing per-task treatment (fast path,
        strategy singles, host-solved classes). Returns ``(solve_ctx,
        placed_remote)``; solve_ctx carries the in-flight device solve
        or is None when the batch fully resolved on host."""
        placed_remote: List[tuple] = []
        solve_ctx = None
        with self.cluster.lock:
            self.cluster.refresh_locked()
            ph.mark("refresh")
            matrix = self.cluster.matrix
            local_slot = matrix.slot_of(self.node_id)
            # Single-alive-node fast path — identical to the single tick.
            if (local_slot is not None
                    and int(matrix.alive.sum()) == 1
                    and bool(matrix.alive[local_slot])):
                for task in batch:
                    if task.cancelled:
                        self._finish_cancelled(task)
                        continue
                    strategy = task.spec.scheduling_strategy
                    if isinstance(strategy, NodeAffinitySchedulingStrategy):
                        slot = self._schedule_one_locked(
                            task, matrix, local_slot)
                    else:
                        req = task.spec.resource_request(self.cluster.ids)
                        slot = (local_slot
                                if self.local_resources.is_feasible(req)
                                else None)
                    if slot is None:
                        self._mark_infeasible(task)
                        continue
                    self._commit_placement(task, slot, matrix,
                                           placed_remote)
                batch = []
            per_class: Dict[int, List[_PendingTask]] = defaultdict(list)
            singles: List[_PendingTask] = []
            for task in batch:
                if task.cancelled:
                    self._finish_cancelled(task)
                elif (task.spec.scheduling_strategy is None
                      and task.spillback_count == 0):
                    per_class[task.spec.scheduling_class].append(task)
                else:
                    singles.append(task)
            threshold = cfg.scheduler_batch_threshold
            big_classes: List[List[_PendingTask]] = []
            for tasks in per_class.values():
                if len(tasks) < threshold:
                    singles.extend(tasks)
                else:
                    big_classes.append(tasks)
            if big_classes:
                reqs = np.stack([
                    tasks[0].spec.resource_request(self.cluster.ids)
                    .dense(matrix.width) for tasks in big_classes])
                ks = np.array([len(tasks) for tasks in big_classes],
                              dtype=np.int64)
                cells = matrix.total.shape[0] * len(big_classes)
                if (cfg.scheduler_use_vectorized_policy
                        and cfg.scheduler_device_solve_min_cells >= 0
                        and cells >= cfg.scheduler_device_solve_min_cells
                        and device_solve_available()):
                    # solve against the device-resident mirror and
                    # return WITHOUT blocking — the pull happens next
                    # iteration, outside every lock (raycheck RC01
                    # posture: no device sync under cluster.lock)
                    mirror = self.cluster.device_mirror_locked()
                    total_d, avail_d, alive_d, _up = mirror.refresh(
                        matrix, cfg.scheduler_matrix_sync_period,
                        cfg.scheduler_pipeline_debug_check)
                    dev = shared_batched_policy(use_jax=True)
                    counts_dev = dev.schedule_tick_fused(
                        reqs, ks, total_d, avail_d, alive_d, local_slot,
                        opts)
                    # the topology epoch this solve saw (lock is held):
                    # _finish_device_batch fences on a mismatch
                    solve_ctx = (big_classes, reqs, counts_dev,
                                 self.cluster.epoch)
                    ph.mark("refresh")
                else:
                    counts = self.batched_policy.schedule_classes(
                        reqs, ks, matrix.total, matrix.available,
                        matrix.alive, local_slot, opts)
                    ph.mark("solve")
                    singles.extend(self._commit_counts(
                        big_classes, counts, matrix, placed_remote))
            for task in singles:
                slot = self._schedule_one_locked(task, matrix, local_slot)
                if slot is None:
                    self._mark_infeasible(task)
                    continue
                self._commit_placement(task, slot, matrix, placed_remote)
            ph.mark("overlap" if solve_ctx is not None else "commit")
        return solve_ctx, placed_remote

    def _finish_device_batch(self, inflight: tuple, ph: _TickPhases,
                             cfg: Config, solving: bool) -> bool:
        """Back half of the pipeline: pull the device counts (the ONE
        device sync point, outside every lock), repair them against the
        current exact int64 availability, and commit/spill the batch
        through the vectorized fan-out.

        Epoch fence: if the cluster topology changed (a node died)
        between the solve's launch and this commit, the device counts
        targeted slots that no longer exist — with
        ``tick_epoch_fencing`` on they are discarded wholesale and the
        batch re-solves on host against the repaired matrix (correct
        but unoverlapped: the price of the fence, paid only on
        topology change). Returns True when this batch was fenced."""
        big_classes, reqs, counts_dev, solve_epoch = inflight
        counts = np.asarray(counts_dev)  # blocks until the solve lands
        ph.mark("solve")
        fenced = False
        placed_remote: List[tuple] = []
        with self.cluster.lock:
            self.cluster.refresh_locked()
            matrix = self.cluster.matrix
            local_slot = matrix.slot_of(self.node_id)
            if (cfg.tick_epoch_fencing
                    and solve_epoch != self.cluster.epoch):
                fenced = True
                from ray_tpu.observability.metrics import tick_epoch_fences
                tick_epoch_fences.inc()
                ks = np.array([len(tasks) for tasks in big_classes],
                              dtype=np.int64)
                counts = self.batched_policy.schedule_classes(
                    reqs, ks, matrix.total, matrix.available,
                    matrix.alive, local_slot,
                    SchedulingOptions.default())
                ph.mark("solve")
            counts = BatchedHybridPolicy.repair_oversubscription(
                reqs, counts, matrix.available)
            leftovers = self._commit_counts(big_classes, counts, matrix,
                                            placed_remote)
            for task in leftovers:
                slot = self._schedule_one_locked(task, matrix, local_slot)
                if slot is None:
                    self._mark_infeasible(task)
                    continue
                self._commit_placement(task, slot, matrix, placed_remote)
            ph.mark("overlap" if solving else "commit")
        if placed_remote:
            self._spillback_batched(placed_remote)
            ph.mark("spillback")
        return fenced

    def _commit_counts(self, big_classes: List[List[_PendingTask]],
                       counts: np.ndarray, matrix: ResourceMatrix,
                       placed_remote: List[tuple]
                       ) -> List[_PendingTask]:
        """Vectorized commit fan-out: group each class's placements by
        target slot with numpy instead of the per-task
        ``zip/iter/flatnonzero`` walk, extend each local dispatch deque
        in ONE locked pass, and collect remote placements for the
        per-raylet batched spillback. Iteration order is exactly the
        old loop's — tasks stay FIFO within their class and slots
        ascend. Returns capacity-exhausted leftovers (the old path's
        ``singles.extend(it)``). Caller holds the cluster lock."""
        leftovers: List[_PendingTask] = []
        local_slot = matrix.slot_of(self.node_id)
        counts = np.asarray(counts, dtype=np.int64)
        local_groups: List[tuple] = []  # (demand key, task group)
        n_local = 0
        for ci, tasks in enumerate(big_classes):
            row = counts[ci]
            nz = np.flatnonzero(row)
            placed = int(row[nz].sum()) if nz.size else 0
            if placed < len(tasks):
                leftovers.extend(tasks[placed:])
                tasks = tasks[:placed]
            if not placed:
                continue
            self.num_scheduled += placed
            bounds = np.cumsum(row[nz])
            # one demand key per class: members share the scheduling
            # class, hence the resource request
            key = tasks[0].spec.resource_request(self.cluster.ids).key()
            for j, slot in enumerate(nz.tolist()):
                group = tasks[int(bounds[j] - row[slot]):int(bounds[j])]
                if slot == local_slot:
                    local_groups.append((key, group))
                    n_local += len(group)
                else:
                    target = self.cluster.raylets.get(matrix.node_at(slot))
                    if target is None:
                        # the node died between solve and commit (epoch
                        # fencing off, or a same-tick race): re-route
                        # the group through the per-task path instead
                        # of crashing the tick thread on a KeyError
                        leftovers.extend(group)
                        continue
                    placed_remote.extend((t, target) for t in group)
        if local_groups:
            with self._lock:
                for key, group in local_groups:
                    q = self._dispatch_queues.get(key)
                    if q is None:
                        # raycheck: disable=RC10 — fed only by committed placements, which submit()'s admission check already bounded
                        q = self._dispatch_queues[key] = deque()
                    q.extend(group)
                self._dispatch_len += n_local
        return leftovers

    def _spillback_batched(self, placed_remote: List[tuple]) -> None:
        """Spillback fan-out, one frame per target raylet: the old loop
        re-submitted one task at a time, re-entering the target's lock
        and tick per task. Group by target and hand each raylet its
        whole batch through :meth:`submit_batch`."""
        by_target: Dict["Raylet", List[_PendingTask]] = {}
        with self._lock:
            for task, raylet in placed_remote:
                self._by_task_id.pop(task.spec.task_id, None)
                by_target.setdefault(raylet, []).append(task)
        self.num_spilled_back += len(placed_remote)
        for raylet, tasks in by_target.items():
            raylet.submit_batch([
                _PendingTask(t.spec, t.on_dispatch, t.spillback_count + 1)
                for t in tasks])

    def _mark_infeasible(self, task: _PendingTask) -> None:
        with self._lock:
            self._infeasible.append(task)
        logger.warning(
            "task %s is infeasible on the cluster (demand=%s)",
            task.spec.name, task.spec.resources)

    def _commit_placement(self, task: _PendingTask, slot: int,
                          matrix: ResourceMatrix,
                          placed_remote: List[tuple]) -> None:
        self.num_scheduled += 1
        target = matrix.node_at(slot)
        if target == self.node_id:
            with self._lock:
                # keyed on the DEMAND (not scheduling_class) so the
                # stop-at-blocked-head dispatch below can never starve a
                # smaller task that shares a class id by accident
                key = task.spec.resource_request(self.cluster.ids).key()
                q = self._dispatch_queues.get(key)
                if q is None:
                    # raycheck: disable=RC10 — fed only by committed placements, which submit()'s admission check already bounded
                    q = self._dispatch_queues[key] = deque()
                q.append(task)
                self._dispatch_len += 1
        else:
            placed_remote.append((task, self.cluster.raylets[target]))

    def _schedule_one_locked(self, task: _PendingTask, matrix: ResourceMatrix,
                             local_slot: int) -> Optional[int]:
        """Pick a node slot for one task. Called under cluster lock."""
        spec = task.spec
        req = spec.resource_request(self.cluster.ids)
        dense = req.dense(matrix.width)
        opts = SchedulingOptions.default()
        strategy = spec.scheduling_strategy
        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            nid = strategy.node_id
            if isinstance(nid, str):
                nid = NodeID.from_hex(nid)
            aff_slot = matrix.slot_of(nid)
            if aff_slot is None and not strategy.soft:
                return None
            opts.node_affinity_slot = aff_slot
            opts.node_affinity_soft = strategy.soft
        elif strategy == "SPREAD":
            opts.spread_strategy = True
        # Forwarded strategy tasks are grant-or-reject: the placing raylet
        # already solved for this node, and re-solving here with this
        # node's own strategy cursors would ping-pong SPREAD tasks
        # between nodes. Plain forwarded tasks get ONE full re-solve
        # (they might fit elsewhere if this node lost capacity in
        # flight), then grant-or-reject on the second hop (reference:
        # direct_task_transport.cc grant_or_reject escalation).
        if task.spillback_count >= (1 if strategy is not None else 2):
            if self.local_resources.is_feasible(req):
                return local_slot
            return None
        slot = self.policy.schedule_one(
            dense, matrix.total, matrix.available, matrix.alive,
            local_slot, opts)
        if slot < 0:
            return None
        if opts.spread_strategy:
            # round-robin for successive SPREAD tasks over nodes with the
            # resources AVAILABLE now; nodes that are merely feasible
            # (total >= demand but saturated) are the fallback only —
            # SPREAD must not land on a busy node while idle ones exist
            # (reference: HybridPolicy spread path prefers available).
            feasible = np.flatnonzero(
                matrix.alive & np.all(matrix.total >= dense, axis=1))
            if len(feasible):
                open_now = feasible[np.all(
                    matrix.available[feasible] >= dense, axis=1)]
                pool = open_now if len(open_now) else feasible
                slot = int(pool[self._spread_rr % len(pool)])
                self._spread_rr += 1
        return slot

    # --------------------------------------------------------- dispatch tick
    def _dispatch_tick(self) -> None:
        """DispatchScheduledTasksToWorkers (cluster_task_manager.cc:295):
        resolve deps, allocate resources, run.

        Two implementations behind the ``dispatch_fastlane_enabled``
        master switch:

        - OFF: the exact per-task loop below — one resource-request
          decode, one allocate, one popleft, one wait_ready callback
          per task — bit-for-bit the pre-fast-lane path.
        - ON: :meth:`_dispatch_tick_fastlane`, which exploits the
          queue key invariant (every member of one dispatch queue has
          an EQUAL resource request) to decode once, allocate in bulk,
          and fan out to workers in batches."""
        if Config.instance().dispatch_fastlane_enabled:
            self._dispatch_tick_fastlane()
            return
        to_start: List[_PendingTask] = []
        with self._lock:
            # Per class: dispatch heads while resources allow, stop the
            # class at its first blocked lease (same-demand members behind
            # it can't fit either).
            for cls in list(self._dispatch_queues):
                q = self._dispatch_queues[cls]
                while q:
                    task = q[0]
                    if task.cancelled:
                        q.popleft()
                        self._dispatch_len -= 1
                        self._finish_cancelled(task)
                        continue
                    req = task.spec.resource_request(self.cluster.ids)
                    if not self.local_resources.allocate(req):
                        break
                    q.popleft()
                    self._dispatch_len -= 1
                    self._running_tasks.add(task)
                    to_start.append(task)
                if not q:
                    del self._dispatch_queues[cls]
        if to_start:
            self.cluster.sync(self)
        for task in to_start:
            self.deps.wait_ready(
                task.spec, lambda t=task: self._run_task(t))

    def _dispatch_tick_fastlane(self) -> None:
        """Bulk per-class dispatch — the fast lane's answer to the 82 %
        dispatch wall (BENCH_r06 ``tick_phase_ms.dispatch``). Dispatch
        queues are keyed on the resource-DEMAND key, so every task in
        one queue carries an equal request: decode it once per class,
        compute how many heads fit with one integer division per
        resource, pop them in bulk, and subtract the whole grant in a
        single pass — O(classes + dispatched) lock work instead of a
        per-task decode + availability scan + allocate + popleft. The
        started tasks enter the running set by identity in one bulk
        ``set.update`` (``finish_task`` frees via the spec's memoized
        request, so nothing is written per task). Stop-at-blocked-head is
        preserved: a class loops until its bulk count comes back zero,
        exactly where the per-task walk would have parked. Worker
        fan-out batches through ``wait_ready_batch`` →
        :meth:`_run_task_batch` so dep-free groups enter the pool under
        one pool-lock acquisition."""
        to_start: List[_PendingTask] = []
        with self._lock:
            avail = self.local_resources.available
            for cls in list(self._dispatch_queues):
                q = self._dispatch_queues[cls]
                while q:
                    head = q[0]
                    if head.cancelled:
                        q.popleft()
                        self._dispatch_len -= 1
                        self._finish_cancelled(head)
                        continue
                    req = head.spec.resource_request(self.cluster.ids)
                    demands = req.demands
                    k = len(q)
                    for rid, amt in demands.items():
                        have = avail.get(rid, 0)
                        if have < amt:
                            k = 0
                            break
                        fit = have // amt
                        if fit < k:
                            k = int(fit)
                    if k <= 0:
                        break
                    if k == len(q):
                        popped = list(q)
                        q.clear()
                    else:
                        popped = [q.popleft() for _ in range(k)]
                    self._dispatch_len -= k
                    # cancelled tasks caught in the bulk pop consume no
                    # grant: count the started ones, charge only those.
                    # The no-cancellation case (nearly always) registers
                    # the whole grant with one C-level set.update — the
                    # task objects themselves are the running markers,
                    # and finish_task recovers the request to free from
                    # the spec's memo, so the registration writes
                    # NOTHING per task.
                    if any(map(_GET_CANCELLED, popped)):
                        started = 0
                        for task in popped:
                            if task.cancelled:
                                self._finish_cancelled(task)
                            else:
                                self._running_tasks.add(task)
                                to_start.append(task)
                                started += 1
                    else:
                        self._running_tasks.update(popped)
                        to_start.extend(popped)
                        started = k
                    if started:
                        for rid, amt in demands.items():
                            avail[rid] = avail.get(rid, 0) - amt * started
                if not q:
                    del self._dispatch_queues[cls]
        if not to_start:
            return
        self.cluster.sync(self)
        wrb = getattr(self.deps, "wait_ready_batch", None)
        if wrb is None:
            for task in to_start:
                self.deps.wait_ready(
                    task.spec, lambda t=task: self._run_task(t))
        else:
            wrb(to_start, self._run_task_batch, self._run_task)

    def _exec_one(self, task: _PendingTask) -> None:
        wid = self.worker_pool.current_worker_id()
        try:
            task.on_dispatch(self, wid)
        finally:
            self.finish_task(task.spec.task_id)

    def _run_task(self, task: _PendingTask) -> None:
        if task.spec.submit_time:
            from ray_tpu.observability.metrics import scheduling_latency

            scheduling_latency.observe(
                time.monotonic() - task.spec.submit_time)
        if not self.worker_pool.submit(self._exec_one, task):
            # node died between placement and execution — hand the task
            # back to the owner (reference: worker death → owner resubmit)
            self.finish_task(task.spec.task_id)
            self._report_lost(task)

    def _run_task_batch(self, tasks: List[_PendingTask]) -> None:
        """Fan a dep-free group out to the worker pool in ONE batched
        enqueue (``WorkerPool.submit_batch``): one pool-lock round trip
        claims/spawns workers for the whole group, and per-task cost
        drops to building an ``(fn, args)`` tuple + a queue put."""
        from ray_tpu.observability.metrics import scheduling_latency

        now = time.monotonic()
        for task in tasks:
            if task.spec.submit_time:
                scheduling_latency.observe(now - task.spec.submit_time)
        items = [(self._exec_one, (task,)) for task in tasks]
        if not self.worker_pool.submit_batch(items):
            for task in tasks:
                self.finish_task(task.spec.task_id)
                self._report_lost(task)

    def finish_task(self, task_id: TaskID) -> None:
        with self._lock:
            task = self._by_task_id.pop(task_id, None)
            if task is not None and task in self._running_tasks:
                self._running_tasks.discard(task)
                # memo hit: every submit path decodes the request once
                # before the task can reach dispatch
                req = task.spec.resource_request(self.cluster.ids)
            else:
                req = None
            if req is not None:
                self.local_resources.free(req)
            # freed-capacity fast path: hand the slot(s) straight to the
            # local dispatch queue (lease handoff) instead of re-running
            # the placement solve per completion. Loop: freeing a large
            # allocation may unblock SEVERAL queued tasks at once.
            handoff: List[_PendingTask] = []
            if req is not None and self._dispatch_len:
                for cls in list(self._dispatch_queues):
                    q = self._dispatch_queues[cls]
                    while q:
                        head = q[0]
                        if head.cancelled:
                            break  # rare: let the full tick reap it
                        head_req = head.spec.resource_request(
                            self.cluster.ids)
                        if not self.local_resources.allocate(head_req):
                            break
                        q.popleft()
                        self._dispatch_len -= 1
                        self._running_tasks.add(head)
                        handoff.append(head)
                    if not q:
                        del self._dispatch_queues[cls]
        if req is not None:
            from ray_tpu.observability.metrics import tasks_finished

            tasks_finished.inc()
            self.cluster.sync(self)
            self.cluster.notify_freed()
            if handoff:
                for next_task in handoff:
                    self.deps.wait_ready(
                        next_task.spec,
                        lambda t=next_task: self._run_task(t))
                with self._lock:
                    more = bool(self._pending)
                if more:
                    self.schedule_tick()
            else:
                self.schedule_tick()

    def _finish_cancelled(self, task: _PendingTask) -> None:
        from ray_tpu.core import runtime as rt_mod

        with self._lock:
            self._by_task_id.pop(task.spec.task_id, None)
        rt = rt_mod.global_runtime
        if rt is not None:
            rt.store_task_cancelled(task.spec)

    # ------------------------------------------------ placement group 2PC
    # Idempotent by (pg_id, bundle_index), like the process tier: a
    # retried prepare does not double-reserve, a duplicated commit does
    # not double-apply shadow capacity, a repeated return does not
    # double-free (reference: placement_group_resource_manager.h's
    # bundle state table).
    def _bundle_key(self, pg_id, bundle_index: int) -> tuple:
        from ray_tpu.scheduler.placement_group import _pg_hex

        return (_pg_hex(pg_id), bundle_index)

    def prepare_bundle(self, pg_id, bundle_index: int,
                       bundle: Dict[str, float]) -> bool:
        """Phase 1: reserve the bundle's raw resources
        (reference: NewPlacementGroupResourceManager::PrepareBundle)."""
        key = self._bundle_key(pg_id, bundle_index)
        req = ResourceRequest.from_map(bundle, self.cluster.ids)
        with self._lock:
            if key in self._pg_bundles:
                return True  # retried prepare: reservation exists
            ok = self.local_resources.allocate(req)
            if ok:
                self._pg_bundles[key] = "prepared"
        if ok:
            self.cluster.sync(self)
        return ok

    def commit_bundle(self, pg_id, bundle_index: int,
                      bundle: Dict[str, float]) -> None:
        """Phase 2: expose the shadow resources tasks schedule against."""
        from ray_tpu.scheduler.placement_group import shadow_resources_for_bundle

        key = self._bundle_key(pg_id, bundle_index)
        with self._lock:
            if self._pg_bundles.get(key) == "committed":
                return  # duplicated commit: applied exactly once
            self._pg_bundles[key] = "committed"
        self.add_capacity(shadow_resources_for_bundle(
            bundle, pg_id, bundle_index))

    def return_bundle(self, pg_id, bundle_index: int,
                      bundle: Dict[str, float], committed: bool = False
                      ) -> None:
        from ray_tpu.scheduler.placement_group import shadow_resources_for_bundle

        key = self._bundle_key(pg_id, bundle_index)
        with self._lock:
            state = self._pg_bundles.pop(key, None)
        if state is None:
            return  # repeated return: already freed
        if committed and state == "committed":
            for name in shadow_resources_for_bundle(bundle, pg_id,
                                                    bundle_index):
                self.remove_capacity(name)
        req = ResourceRequest.from_map(bundle, self.cluster.ids)
        with self._lock:
            self.local_resources.free(req)
        self.cluster.sync(self)
        self.schedule_tick()

    # ------------------------------------------------- resource manipulation
    def adjust_resources(self, deltas: Dict[str, float],
                         allocate: bool) -> bool:
        """Allocate (True) or free (False) resources outside a task's own
        demand — used for actor lifetime downgrades and PG bundles."""
        req = ResourceRequest.from_map(deltas, self.cluster.ids)
        with self._lock:
            if allocate:
                ok = self.local_resources.allocate(req)
            else:
                self.local_resources.free(req)
                ok = True
        self.cluster.sync(self)
        if not allocate:
            self.schedule_tick()
        return ok

    def add_capacity(self, resources: Dict[str, float]) -> None:
        with self._lock:
            for name, amount in resources.items():
                rid = self.cluster.ids.get_id(name)
                from ray_tpu.scheduler.resources import to_fixed

                self.local_resources.add_capacity(rid, to_fixed(amount))
        self.cluster.sync(self)
        self.retry_infeasible()

    def remove_capacity(self, resource_name: str) -> None:
        with self._lock:
            rid = self.cluster.ids.get_id(resource_name)
            self.local_resources.remove_capacity(rid)
        self.cluster.sync(self)

    def retry_infeasible(self) -> None:
        with self._lock:
            infeasible, self._infeasible = self._infeasible, []
            self._pending.extend(infeasible)
        if infeasible:
            self.schedule_tick()

    def _report_lost(self, task: _PendingTask) -> None:
        from ray_tpu.core import runtime as rt_mod

        rt = rt_mod.global_runtime
        if rt is not None:
            rt.resubmit_lost_task(task.spec)

    def extract_outstanding(self) -> List[_PendingTask]:
        """Drain every task that has not started running — called when
        this node dies so the owner can resubmit (reference: raylet death
        fails leases; CoreWorker retries)."""
        with self._lock:
            out = list(self._pending) + list(self._infeasible)
            for q in self._dispatch_queues.values():
                out.extend(q)
            running = self._running_tasks
            self._pending.clear()
            self._dispatch_queues.clear()
            self._dispatch_len = 0
            self._infeasible.clear()
            seen = {t.spec.task_id for t in out}
            for task_id, task in list(self._by_task_id.items()):
                if task not in running and task_id not in seen:
                    out.append(task)
            self._by_task_id.clear()
        return out

    # ------------------------------------------------------------- lifecycle
    def drain(self, timeout: float = 5.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not (self._pending or self._dispatch_len
                        or self._running_tasks):
                    return True
            time.sleep(0.001)
        return False

    def shutdown(self) -> None:
        self.dead = True
        self.worker_pool.shutdown()

    def debug_state(self) -> dict:
        with self._lock:
            return {
                "node_id": self.node_id.hex(),
                "pending": len(self._pending),
                "dispatch_queue": self._dispatch_len,
                "infeasible": len(self._infeasible),
                "running": len(self._running_tasks),
                "num_scheduled": self.num_scheduled,
                "num_spilled_back": self.num_spilled_back,
                "available": self.local_resources.to_map(
                    self.cluster.ids, available=True),
                "total": self.local_resources.to_map(self.cluster.ids),
            }
