"""ray_tpu — a TPU-native distributed execution framework.

Dynamic task graphs (``@ray_tpu.remote``), stateful actors, an
ownership-based distributed object store, placement groups, and a library
tier (train/tune/data/serve/workflow) built idiomatically on
JAX/XLA/Pallas/pjit. The scheduling plane — per-node bin-packing, the
placement-group packer, and the object-pull admission queue — runs as
batched vectorized kernels.

Public API mirrors the reference framework (python/ray/__init__.py) so a
user of the reference can switch with an import change.
"""

__version__ = "0.1.0"

from ray_tpu._private.ids import (  # noqa: F401
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
    UniqueID,
    WorkerID,
)
from ray_tpu import exceptions  # noqa: F401

# The core runtime API (init/remote/get/put/wait/...) is re-exported from
# ray_tpu.core.api once that module is imported; keep the import at the
# bottom to avoid cycles.
from ray_tpu.core.api import (  # noqa: F401,E402
    ObjectRef,
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    method,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)


def timeline(filename: str = "ray_tpu_timeline.json") -> str:
    """Dump the Chrome-trace timeline (reference: ray.timeline)."""
    from ray_tpu.observability import timeline as _timeline

    return _timeline(filename)


def get_gpu_ids():
    """Accelerator ids assigned to this worker (reference: ray.get_gpu_ids).

    SHIM — index-count-only: this runtime does not pin specific device
    ordinals to workers (all workers of a node share the node's device
    set; JAX addresses devices through the mesh, not through
    CUDA_VISIBLE_DEVICES-style masking), so the returned ids are always
    ``0..k-1`` where ``k`` is the ceil of the worker's GPU/TPU resource
    assignment — NOT a per-worker device selection. Code that uses the
    reference's contract (ids index into the node's physical devices
    assigned exclusively to this worker) should use the mesh/sharding
    APIs instead. A fractional assignment still owns (a share of) one
    device. See PARITY.md."""
    import math

    ctx = get_runtime_context()
    assigned = ctx.get_assigned_resources()
    n = float(assigned.get("GPU", assigned.get("TPU", 0)))
    return list(range(math.ceil(n)))


__all__ = [
    "ActorID", "JobID", "NodeID", "ObjectID", "PlacementGroupID", "TaskID",
    "UniqueID", "WorkerID", "ObjectRef", "exceptions", "init", "shutdown",
    "is_initialized", "remote", "get", "put", "wait", "kill", "cancel",
    "get_actor", "method", "nodes", "cluster_resources",
    "available_resources", "get_runtime_context", "timeline",
    "get_gpu_ids", "__version__",
]
