"""Flash attention: Pallas TPU forward kernel + blockwise custom VJP.

The hot op of the model family. Three tiers behind one call:

  flash_attention(q, k, v, causal=...)
    -> Pallas kernel on TPU (tiled over the MXU, online softmax, O(S)
       memory), selected when the default backend is TPU;
    -> blockwise lax.scan implementation elsewhere (same math, XLA-fused;
       also the correctness oracle for the kernel);
  backward: Pallas dq/dk/dv kernels on TPU (flash-attention-2 split,
  causal fetch-trim), blockwise recomputation elsewhere — both
  recompute p from the saved logsumexp, so training never materializes
  the [S, S] attention matrix regardless of tier.

Layouts: [batch, seq, heads, head_dim] throughout (matches
parallel/ring_attention.py, which wraps this per-shard).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

# Per-path block defaults, resolved in _fwd_dispatch/_flash_bwd when the
# caller passes None. The PALLAS kernels want big blocks — at (256, 512)
# x d=128 the VMEM working set is ~1 MB of a ~16 MB budget, and larger K
# blocks amortize per-grid-step overhead 128x128 paid 4x as often. An
# r05 live-v5e sweep over (block_q, block_k) in {128..2048}^2 at
# B4-S2048-H8-D128 and B8-S2048-H16-D128 found no candidate beating
# (256, 512) outside tunnel measurement noise (~±20% run-to-run), so it
# stays; the same sweep showed the kernel 3x faster than the blockwise
# tier at the larger shape (5.9-6.5 ms vs 18.8 ms — blockwise's fp32
# [B,H,Sq,block_k] logits temporaries grow with batch x heads). The
# BLOCKWISE path keeps 128: its logits temporary scales with block_k,
# and 128 is the measured-good setting — the two paths must not share
# a knob or tuning one regresses the other's memory/perf profile.
DEFAULT_BLOCK_Q = None
DEFAULT_BLOCK_K = None
PALLAS_BLOCK_Q = 256
PALLAS_BLOCK_K = 512
BLOCKWISE_BLOCK_K = 128
_NEG_INF = -1e30


# Test hook: force the Pallas kernels through the interpreter so the
# CPU suite exercises kernel code paths (pl.pallas_call(interpret=True)).
_FORCE_INTERPRET = False


def _use_pallas() -> bool:
    """Whether the Pallas forward kernel dispatches. Default 'auto'
    resolves to the PALLAS KERNEL on TPU, on measured evidence
    (round 5, live v5e): after the round-4 bf16 fix the standalone
    kernel forward is 1.9x faster than blockwise (26.4 ms vs 50.8 ms
    at B4-S2048-H8-D128) and the full NON-remat train step wins with
    it in repeated A/Bs (931/987 ms vs 962/1003 ms, MFU 0.086 vs 0.083
    at L8-H1024-S2048-B8). An early 127M-scale A/B suggested blockwise
    was ~8% faster under jax.checkpoint/remat, but at the flagship
    config the kernel wins remat too, decisively: 632M L12-H2048
    B32-remat measures MFU 0.304 with the kernel vs 0.234 with
    RAY_TPU_ATTN_FWD=blockwise (same run conditions, r05 sweep) — the
    blockwise tier's fp32 [B,H,Sq,block_k] logits temporaries dominate
    once batch x heads grow. The kernels stay correctness-tested in
    interpret mode and both tiers stay benchmarked by bench.py."""
    if _FORCE_INTERPRET:
        return True
    import os

    mode = os.environ.get("RAY_TPU_ATTN_FWD", "auto")
    if mode == "blockwise":
        return False
    if mode not in ("auto", "pallas"):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ===========================================================================
# Blockwise pure-JAX implementation (oracle + CPU path). Returns (out, lse).
# ===========================================================================


def _pad_kv(k, v, block_k: int):
    """Zero-pad K/V so every block slice is in-bounds — a clamped
    dynamic_slice on a partial final block would attribute rows to wrong
    key positions (the `k_pos < sk` mask handles the padding)."""
    sk = k.shape[1]
    pad = (-sk) % block_k
    if pad:
        cfg = [(0, 0), (0, pad), (0, 0), (0, 0)]
        k = jnp.pad(k, cfg)
        v = jnp.pad(v, cfg)
    return k, v


def _blockwise_fwd(q, k, v, causal: bool, sm_scale: float, block_k: int):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_k = min(block_k, sk)
    num_kb = (sk + block_k - 1) // block_k
    k, v = _pad_kv(k, v, block_k)
    qf = q.astype(jnp.float32)
    q_pos = jnp.arange(sq)

    def kv_step(carry, kb):
        acc, m_run, l_run = carry
        start = kb * block_k
        k_blk = lax.dynamic_slice_in_dim(k, start, block_k, axis=1)
        v_blk = lax.dynamic_slice_in_dim(v, start, block_k, axis=1)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf,
                            k_blk.astype(jnp.float32)) * sm_scale
        k_pos = start + jnp.arange(block_k)
        valid = k_pos < sk
        if causal:
            valid = valid[None, :] & (q_pos[:, None] >= k_pos[None, :])
        else:
            valid = jnp.broadcast_to(valid[None, :], (sq, block_k))
        logits = jnp.where(valid[None, None], logits, _NEG_INF)
        m_new = jnp.maximum(m_run, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        alpha = jnp.exp(m_run - m_new)
        l_new = alpha * l_run + jnp.sum(p, axis=-1)
        acc = (acc * jnp.transpose(alpha, (0, 2, 1))[..., None]
               + jnp.einsum("bhqk,bkhd->bqhd", p,
                            v_blk.astype(jnp.float32)))
        return (acc, m_new, l_new), None

    # derive the initial carries from the inputs so their device-varying
    # set matches the body under any enclosing shard_map (see
    # parallel/ring_attention.py for the same pattern)
    acc0 = jnp.zeros_like(qf)
    base = jnp.transpose(qf.sum(-1), (0, 2, 1)) * 0.0
    m0 = base + _NEG_INF
    l0 = base
    (acc, m_run, l_run), _ = lax.scan(
        kv_step, (acc0, m0, l0), jnp.arange(num_kb))
    l_safe = jnp.maximum(l_run, 1e-20)
    out = acc / jnp.transpose(l_safe, (0, 2, 1))[..., None]
    lse = m_run + jnp.log(l_safe)  # [B, H, Sq]
    return out.astype(q.dtype), lse


def _blockwise_bwd(q, k, v, out, lse, dout, causal: bool, sm_scale: float,
                   block_k: int):
    """dq/dk/dv from saved lse, one KV block at a time."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_k = min(block_k, sk)
    num_kb = (sk + block_k - 1) // block_k
    k_pad, v_pad = _pad_kv(k, v, block_k)
    qf, of, dof = (x.astype(jnp.float32) for x in (q, out, dout))
    delta = jnp.einsum("bqhd,bqhd->bhq", of, dof)  # [B,H,Sq]
    q_pos = jnp.arange(sq)

    def kv_step(carry, kb):
        dq_acc, dk_acc, dv_acc = carry
        start = kb * block_k
        k_blk = lax.dynamic_slice_in_dim(k_pad, start, block_k, axis=1
                                         ).astype(jnp.float32)
        v_blk = lax.dynamic_slice_in_dim(v_pad, start, block_k, axis=1
                                         ).astype(jnp.float32)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk) * sm_scale
        k_pos = start + jnp.arange(block_k)
        valid = k_pos < sk
        if causal:
            valid = valid[None, :] & (q_pos[:, None] >= k_pos[None, :])
        else:
            valid = jnp.broadcast_to(valid[None, :], (sq, block_k))
        p = jnp.where(valid[None, None],
                      jnp.exp(logits - lse[..., None]), 0.0)  # [B,H,q,k]
        dv_blk = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
        dp = jnp.einsum("bqhd,bkhd->bhqk", dof, v_blk)
        ds = p * (dp - delta[..., None]) * sm_scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bkhd->bqhd", ds, k_blk)
        dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
        dk_acc = lax.dynamic_update_slice_in_dim(dk_acc, dk_blk, start,
                                                 axis=1)
        dv_acc = lax.dynamic_update_slice_in_dim(dv_acc, dv_blk, start,
                                                 axis=1)
        return (dq_acc, dk_acc, dv_acc), None

    dq0 = jnp.zeros_like(qf)
    dk0 = jnp.zeros_like(k_pad, dtype=jnp.float32)
    dv0 = jnp.zeros_like(v_pad, dtype=jnp.float32)
    (dq, dk, dv), _ = lax.scan(kv_step, (dq0, dk0, dv0), jnp.arange(num_kb))
    dk = dk[:, :sk]
    dv = dv[:, :sk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ===========================================================================
# Pallas TPU forward kernel.
# ===========================================================================


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                  acc_scr, *, causal: bool, sm_scale: float, block_q: int,
                  block_k: int, num_kb: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = True
    if causal:
        # skip blocks strictly above the diagonal
        run = (ki * block_k) <= (qi * block_q + block_q - 1)

    @pl.when(run)
    def _compute():
        # operands stay in their NATIVE dtype: the MXU multiplies bf16
        # at 4x its fp32 rate and accumulates in fp32 via
        # preferred_element_type — casting inputs up front (the round-3
        # version) forfeited exactly that 4x and is why the kernel lost
        # to the XLA blockwise path
        q = q_ref[0]                               # [block_q, d]
        k = k_ref[0]                               # [block_k, d]
        v = v_ref[0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(q_pos >= k_pos, logits, _NEG_INF)
        m_prev = m_scr[:]                          # [block_q, 1]
        m_new = jnp.maximum(m_prev[:, 0], jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[:, None])
        alpha = jnp.exp(m_prev[:, 0] - m_new)
        l_new = alpha * l_scr[:][:, 0] + jnp.sum(p, axis=-1)
        acc_scr[:] = (acc_scr[:] * alpha[:, None]
                      + jax.lax.dot_general(
                          # P in the value dtype for a full-rate MXU
                          # pass; the accumulator itself stays fp32
                          p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32))
        m_scr[:] = m_new[:, None]
        l_scr[:] = l_new[:, None]

    @pl.when(ki == num_kb - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:][:, 0], 1e-20)
        o_ref[0] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[:][:, 0] + jnp.log(l_safe))[None, :].reshape(
            lse_ref.shape[1:])


def _causal_kv_index_map(block_q: int, block_k: int, num_kb: int):
    """BlockSpec index map for K/V under a (bh, qi, ki) grid with the
    causal fetch-trim: blocks strictly above the diagonal are
    compute-skipped by the kernels' ``pl.when``, so clamp their fetch
    index to the q-row's last needed block — an unchanged index between
    grid steps makes the Pallas pipeline elide the DMA (37.5% of K/V
    fetches never issued at the default blocks on S2048). The outer
    min with num_kb-1 covers sq > sk, where trailing q rows' diagonal
    lies beyond the last K block. Shared by the forward and dq kernels
    (the r05 review flagged three hand-copied variants)."""

    def index(bh, qi, ki):
        kmax = jnp.minimum((qi * block_q + block_q - 1) // block_k,
                           num_kb - 1)
        return (bh, jnp.minimum(ki, kmax), 0)

    return index


def _causal_q_min(block_q: int, block_k: int, num_qb: int, ki):
    """First q block at or below the diagonal for K row ``ki`` (the
    dk/dv kernel iterates qi innermost and skips the EARLY q blocks:
    run ⟺ qi*bq + bq - 1 >= ki*bk ⟺ qi >= (ki*bk) // bq). Min with
    num_qb-1 covers sk > sq, where trailing K rows have no computed q
    block at all."""
    return jnp.minimum((ki * block_k) // block_q, num_qb - 1)


def _pallas_fwd(q, k, v, causal: bool, sm_scale: float,
                block_q: int, block_k: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0, (
        "flash_attention requires seq divisible by block size")
    num_qb = sq // block_q
    num_kb = sk // block_k
    # layout: fold batch*heads into grid dim 0 with [B*H, S, D] views
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)

    kernel = functools.partial(
        _flash_kernel, causal=causal, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, num_kb=num_kb)

    if causal:
        kv_index = _causal_kv_index_map(block_q, block_k, num_kb)
    else:
        def kv_index(bh, qi, ki):
            return (bh, ki, 0)

    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_FORCE_INTERPRET,
    )(qt, kt, vt)
    out = out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    lse = lse.reshape(b, h, sq)
    return out, lse


# ===========================================================================
# Pallas TPU backward kernels (flash-attention-2 split: one kernel
# accumulates dq over KV blocks, a second accumulates dk/dv over Q blocks;
# both recompute p from the saved logsumexp so the [S, S] matrix never
# materializes — the blockwise math at _blockwise_bwd is the spec).
# ===========================================================================


def _bwd_dq_kernel(q_ref, k_ref, v_ref, lse_ref, delta_ref, do_ref, dq_ref,
                   dq_scr, *, causal: bool, sm_scale: float, block_q: int,
                   block_k: int, num_kb: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True
    if causal:
        run = (ki * block_k) <= (qi * block_q + block_q - 1)

    @pl.when(run)
    def _compute():
        # native-dtype operands + fp32 accumulation (see _flash_kernel)
        q = q_ref[0]                                 # [bq, d]
        k = k_ref[0]                                 # [bk, d]
        v = v_ref[0]
        do = do_ref[0]                               # [bq, d]
        lse = lse_ref[0][0]                          # [bq]
        delta = delta_ref[0][0]                      # [bq]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(q_pos >= k_pos, logits, _NEG_INF)
        p = jnp.exp(logits - lse[:, None])           # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # [bq, bk]
        ds = p * (dp - delta[:, None]) * sm_scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_kb - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, lse_ref, delta_ref, do_ref,
                     dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                     sm_scale: float, block_q: int, block_k: int,
                     num_qb: int):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        run = (ki * block_k) <= (qi * block_q + block_q - 1)

    @pl.when(run)
    def _compute():
        # native-dtype operands + fp32 accumulation (see _flash_kernel)
        q = q_ref[0]                                 # [bq, d]
        k = k_ref[0]                                 # [bk, d]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][0]
        delta = delta_ref[0][0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(q_pos >= k_pos, logits, _NEG_INF)
        p = jnp.exp(logits - lse[:, None])           # [bq, bk]
        # dv += p.T @ do
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale    # [bq, bk]
        # dk += ds.T @ q
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_qb - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _pallas_bwd(q, k, v, out, lse, dout, causal: bool, sm_scale: float,
                block_q: int, block_k: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    num_qb = sq // block_q
    num_kb = sk // block_k
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    dot = dout.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    lse_t = lse.reshape(b * h, 1, sq)
    delta = jnp.einsum("bqhd,bqhd->bhq", out.astype(jnp.float32),
                       dout.astype(jnp.float32)).reshape(b * h, 1, sq)

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0))
    if causal:
        bwd_kv_index = _causal_kv_index_map(block_q, block_k, num_kb)
    else:
        def bwd_kv_index(bh, qi, ki):
            return (bh, ki, 0)
    k_spec = pl.BlockSpec((1, block_k, d), bwd_kv_index)
    row_spec = pl.BlockSpec((1, 1, block_q), lambda bh, qi, ki: (bh, 0, qi))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k, num_kb=num_kb),
        grid=(b * h, num_qb, num_kb),
        in_specs=[q_spec, k_spec, k_spec, row_spec, row_spec, q_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_FORCE_INTERPRET,
    )(qt, kt, vt, lse_t, delta, dot)

    if causal:
        # dk/dv iterates qi innermost and skips the EARLY q blocks
        # strictly above the diagonal: clamp skipped leading fetches of
        # Q/do/lse/delta up to the first needed block (_causal_q_min)
        # so their copies are elided too
        def bwd_q_index(bh, ki, qi):
            qmin = _causal_q_min(block_q, block_k, num_qb, ki)
            return (bh, jnp.maximum(qi, qmin), 0)

        def bwd_row_index(bh, ki, qi):
            qmin = _causal_q_min(block_q, block_k, num_qb, ki)
            return (bh, 0, jnp.maximum(qi, qmin))
    else:
        def bwd_q_index(bh, ki, qi):
            return (bh, qi, 0)

        def bwd_row_index(bh, ki, qi):
            return (bh, 0, qi)
    kq_spec = pl.BlockSpec((1, block_q, d), bwd_q_index)
    kk_spec = pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0))
    krow_spec = pl.BlockSpec((1, 1, block_q), bwd_row_index)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, causal=causal,
                          sm_scale=sm_scale, block_q=block_q,
                          block_k=block_k, num_qb=num_qb),
        grid=(b * h, num_kb, num_qb),
        in_specs=[kq_spec, kk_spec, kk_spec, krow_spec, krow_spec, kq_spec],
        out_specs=[kk_spec, kk_spec],
        out_shape=[jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, sk, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_FORCE_INTERPRET,
    )(qt, kt, vt, lse_t, delta, dot)

    dq = dq.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    dk = dk.reshape(b, h, sk, d).transpose(0, 2, 1, 3)
    dv = dv.reshape(b, h, sk, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


# ===========================================================================
# Public op with custom VJP.
# ===========================================================================


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K):
    out, _ = _fwd_dispatch(q, k, v, causal, sm_scale, block_q, block_k)
    return out


def _pallas_tileable(sq: int, sk: int, block_q: int, block_k: int) -> bool:
    """Mosaic requires each block's trailing dims to divide into (8, 128)
    tiles or equal the array dim; the lse output block (1, 1, block_q)
    additionally needs block_q % 128 == 0 unless block_q == sq."""
    bq, bk = min(block_q, sq), min(block_k, sk)
    if sq % bq or sk % bk:
        return False
    if not (bq == sq or bq % 8 == 0) or not (bk == sk or bk % 8 == 0):
        return False
    if not (bq == sq or bq % 128 == 0):
        return False
    return sq >= 8 and sk >= 8


def _fwd_dispatch(q, k, v, causal, sm_scale, block_q, block_k):
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    pq = block_q or PALLAS_BLOCK_Q
    pk = block_k or PALLAS_BLOCK_K
    if _use_pallas() and _pallas_tileable(q.shape[1], k.shape[1], pq, pk):
        return _pallas_fwd(q, k, v, causal, scale, pq, pk)
    return _blockwise_fwd(q, k, v, causal, scale,
                          block_k or BLOCKWISE_BLOCK_K)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out, lse = _fwd_dispatch(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v, out, lse)


def _bwd_impl() -> str:
    """Backward tier: 'auto' (default) resolves BY HEAD DIM on TPU —
    Pallas dq/dk/dv kernels at head_dim >= 128 AND head_dim % 128 == 0
    (full lane utilization), blockwise otherwise.
    Measured on live v5e (r05), the discriminator is lane utilization:
    at d=128 the trimmed kernels are the decisive flagship winner
    (632M L12-H2048-B40, head_dim 128: MFU 0.409/0.411 vs 0.319 with
    the blockwise backward, two runs each — blockwise's fp32
    [B,H,Sq,block_k] logits temporaries dominate once batch x heads
    grow), but at d=64 the two-kernel split runs blocks at half the
    128-wide lane dim and LOSES (H1024-16-head MoE step, head_dim 64:
    2.74 s vs 2.17 s blockwise; the r03 'blockwise wins' A/B was the
    same d=64 shape). RAY_TPU_ATTN_BWD=pallas|blockwise forces a
    tier; both stay correctness-tested against each other."""
    import os

    return os.environ.get("RAY_TPU_ATTN_BWD", "auto")


def _flash_bwd(causal, sm_scale, block_q, block_k, residuals, dout):
    q, k, v, out, lse = residuals
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    pq = block_q or PALLAS_BLOCK_Q
    pk = block_k or PALLAS_BLOCK_K
    impl = _bwd_impl()
    # auto requires head_dim to be a MULTIPLE of the 128-wide lane dim,
    # not merely >= 128: the measured rationale is lane utilization, and
    # a non-multiple dim (e.g. d=160, the xl 16-head shape: r05 MFU
    # 0.300 vs 0.4045 at d=128) pads blocks to partial lanes — it gets
    # the reference/blockwise path until a measurement says otherwise.
    # RAY_TPU_ATTN_BWD=pallas still forces the kernels for A/B runs.
    want_pallas = (impl == "pallas"
                   or (impl == "auto" and q.shape[-1] >= 128
                       and q.shape[-1] % 128 == 0))
    if (want_pallas and _use_pallas()
            and _pallas_tileable(q.shape[1], k.shape[1], pq, pk)):
        return _pallas_bwd(q, k, v, out, lse, dout, causal, scale,
                           pq, pk)
    dq, dk, dv = _blockwise_bwd(q, k, v, out, lse, dout, causal, scale,
                                block_k or BLOCKWISE_BLOCK_K)
    return dq, dk, dv


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def attention_reference(q, k, v, causal: bool = True,
                        sm_scale: Optional[float] = None):
    """O(S^2)-memory reference implementation for tests."""
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
