"""Elementwise / normalization building blocks.

Pure jnp: XLA fuses these into surrounding matmuls on TPU (HBM-bandwidth
friendly), so no hand kernel is needed; the hot op with real tiling needs
is attention (ops/attention.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale * weight.astype(jnp.float32)).astype(dtype)


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 10000.0):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                           / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)  # [S, D/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               position_offset: int | jax.Array = 0) -> jax.Array:
    """x: [B, S, H, D]; cos/sin: [max_seq, D/2] (sliced by position)."""
    s = x.shape[1]
    if isinstance(position_offset, int) and position_offset == 0:
        c = cos[:s]
        sn = sin[:s]
    else:
        c = jax.lax.dynamic_slice_in_dim(cos, position_offset, s, axis=0)
        sn = jax.lax.dynamic_slice_in_dim(sin, position_offset, s, axis=0)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    c = c[None, :, None, :]
    sn = sn[None, :, None, :]
    out = jnp.concatenate([x1 * c - x2 * sn, x1 * sn + x2 * c], axis=-1)
    return out.astype(x.dtype)


def swiglu(x: jax.Array, w_gate: jax.Array, w_up: jax.Array,
           w_down: jax.Array) -> jax.Array:
    """SwiGLU MLP: silu(x@w_gate) * (x@w_up) @ w_down."""
    gate = jax.nn.silu(jnp.einsum("...h,hm->...m", x, w_gate))
    up = jnp.einsum("...h,hm->...m", x, w_up)
    return jnp.einsum("...m,mh->...h", gate * up, w_down)
