"""Multi-node test cluster on one host.

Mirrors the reference's python/ray/cluster_utils.py:101 ``Cluster``
(add_node:170, remove_node:244): nodes share one control plane; killing a
node exercises failure detection, actor restart and object recovery. The
in-process implementation backs each node with a thread-pool raylet; the
multiprocess runtime substitutes OS-process nodes behind the same API.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core import runtime as rt_mod
from ray_tpu.core.raylet import Raylet


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        self.head_node: Optional[Raylet] = None
        self.worker_nodes: List[Raylet] = []
        self._rt = None
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    def add_node(self, num_cpus: float = 1, num_gpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 object_store_memory: Optional[int] = None, **kwargs
                 ) -> Raylet:
        if self._rt is None:
            from ray_tpu.core.api import init

            self._rt = init(num_cpus=num_cpus, num_gpus=num_gpus,
                            resources=resources,
                            object_store_memory=object_store_memory)
            self.head_node = self._rt.head_raylet
            return self.head_node
        node_resources = dict(resources or {})
        node_resources.setdefault("CPU", num_cpus)
        if num_gpus:
            node_resources["GPU"] = num_gpus
        node = self._rt.add_node(node_resources)
        self.worker_nodes.append(node)
        return node

    def remove_node(self, node: Raylet) -> None:
        if self._rt is None:
            return
        self._rt.remove_node(node.node_id)
        if node in self.worker_nodes:
            self.worker_nodes.remove(node)

    def drain_node(self, node: Raylet,
                   deadline_s: Optional[float] = None) -> None:
        """Graceful removal (drain plane): placement excludes the node
        immediately, in-flight work gets the drain deadline to finish,
        then the node is removed (reference: the autoscaler's
        drain-before-terminate path)."""
        if self._rt is None:
            return
        self._rt.drain_node(node.node_id, deadline_s=deadline_s)
        if node in self.worker_nodes:
            self.worker_nodes.remove(node)

    def wait_for_nodes(self, timeout: float = 10.0) -> None:
        pass  # in-process nodes register synchronously

    @property
    def address(self) -> str:
        return "local"

    def shutdown(self) -> None:
        from ray_tpu.core.api import shutdown

        shutdown()
        self._rt = None
