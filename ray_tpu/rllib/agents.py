"""Trainers: PPOTrainer + DQNTrainer.

Reference: rllib/agents/trainer.py + agents/ppo/ppo.py, agents/dqn/dqn.py
*as API surface* — the execution plan here is the classic synchronous
loop: parallel rollouts on the worker fleet → concat → learn on the
local worker → broadcast weights. Trainers implement the Tune Trainable
protocol (train/save_checkpoint/restore) so `tune.run(PPOTrainer, ...)`
works unchanged.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Type

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.policy import DQNPolicy, PPOPolicy, Policy
from ray_tpu.rllib.rollout_worker import ReplayBuffer, WorkerSet
from ray_tpu.rllib.sample_batch import SampleBatch

COMMON_CONFIG: Dict[str, Any] = {
    "env": None,
    "env_config": {},
    "num_workers": 2,
    "rollout_fragment_length": 200,
    "train_batch_size": 400,
    "seed": 0,
}


class Trainer:
    _policy_cls: Type[Policy] = None
    _default_config: Dict[str, Any] = COMMON_CONFIG

    def __init__(self, config: Optional[dict] = None,
                 env: Any = None):
        self.config = dict(self._default_config)
        self.config.update(config or {})
        if env is not None:
            self.config["env"] = env
        if self.config["env"] is None:
            raise ValueError("config['env'] is required")
        self.workers = WorkerSet(
            self.config["env"], self._policy_cls,
            num_workers=self.config["num_workers"],
            policy_config=self.config.get("policy_config", {}),
            env_config=self.config.get("env_config", {}))
        self.workers.sync_weights()
        self._iteration = 0
        self._timesteps_total = 0

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        learner_stats = self.training_step()
        self._iteration += 1
        metrics = self.workers.remote_metrics()
        rewards = [m["episode_reward_mean"] for m in metrics
                   if not np.isnan(m["episode_reward_mean"])]
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._timesteps_total,
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else float("nan"),
            "episodes_total": sum(m["episodes_total"] for m in metrics),
            "time_this_iter_s": time.perf_counter() - t0,
            "info": {"learner": learner_stats},
        }

    def training_step(self) -> Dict[str, float]:
        raise NotImplementedError

    # shared execution-plan pieces -------------------------------------
    def _per_worker(self, total_steps: int) -> int:
        return max(1, total_steps
                   // max(len(self.workers.remote_workers), 1))

    def _onpolicy_step(self, num_sgd_iter: int = 1,
                       per_fragment: bool = False) -> Dict[str, float]:
        """sample -> learn -> broadcast (the synchronous execution plan
        shared by PPO/A2C/IMPALA). per_fragment keeps worker fragments
        separate for algorithms whose math scans time within one
        trajectory (V-trace)."""
        per_worker = self._per_worker(self.config["train_batch_size"])
        stats: Dict[str, float] = {}
        if per_fragment:
            batches = self.workers.sample_parallel_batches(per_worker)
            for _ in range(num_sgd_iter):
                for fragment in batches:
                    stats = self.workers.local_worker.learn_on_batch(
                        fragment)
            self._timesteps_total += sum(b.count for b in batches)
        else:
            batch = self.workers.sample_parallel(per_worker)
            self._timesteps_total += batch.count
            for _ in range(num_sgd_iter):
                stats = self.workers.local_worker.learn_on_batch(batch)
        self.workers.sync_weights()
        return stats

    def _replay_step(self) -> Dict[str, float]:
        """store -> sample -> train (the replay execution plan shared by
        DQN/SAC)."""
        per_worker = self._per_worker(
            self.config["rollout_fragment_length"])
        batch = self.workers.sample_parallel(per_worker)
        self._timesteps_total += batch.count
        self.replay.add_batch(batch)
        stats: Dict[str, float] = {}
        if len(self.replay) >= self.config["learning_starts"]:
            for _ in range(self.config["sgd_steps_per_iter"]):
                stats = self.workers.local_worker.learn_on_batch(
                    self.replay.sample(self.config["sgd_batch_size"]))
            self.workers.sync_weights()
        return stats

    # ----------------------------------------------- tune Trainable shims
    def save_checkpoint(self) -> dict:
        return {"weights": self.workers.local_worker.get_weights(),
                "iteration": self._iteration}

    def restore(self, checkpoint: dict) -> None:
        self.workers.local_worker.set_weights(checkpoint["weights"])
        self._iteration = checkpoint["iteration"]
        self.workers.sync_weights()

    def get_policy(self) -> Policy:
        return self.workers.local_worker.policy

    def compute_single_action(self, obs) -> int:
        actions, _ = self.get_policy().compute_actions(obs)
        return int(actions[0])

    def stop(self) -> None:
        self.workers.stop()


class PPOTrainer(Trainer):
    _policy_cls = PPOPolicy
    _default_config = {**COMMON_CONFIG, "policy_config": {}}

    def training_step(self) -> Dict[str, float]:
        return self._onpolicy_step()


class A2CTrainer(Trainer):
    """Synchronous advantage actor-critic (reference: agents/a3c run in
    its synchronous configuration)."""

    _policy_cls = None  # set below (import ordering)
    _default_config = {**COMMON_CONFIG, "policy_config": {}}

    def training_step(self) -> Dict[str, float]:
        return self._onpolicy_step()


class IMPALATrainer(Trainer):
    """Importance-weighted actor-learner: the fleet keeps sampling with
    the weights it has (stale by up to one sync) and V-trace corrects
    at the learner (reference: agents/impala/impala.py). Weights
    broadcast once per iteration, not per batch, so sampling and
    learning overlap."""

    _policy_cls = None
    _default_config = {**COMMON_CONFIG, "policy_config": {},
                      "num_sgd_iter": 2}

    def training_step(self) -> Dict[str, float]:
        # per_fragment: V-trace scans time within a fragment; gluing two
        # workers' unrelated fragments would leak corrections across the
        # boundary
        return self._onpolicy_step(self.config["num_sgd_iter"],
                                   per_fragment=True)


class APPOTrainer(IMPALATrainer):
    """Asynchronous PPO (reference: agents/ppo/appo.py): IMPALA's
    execution plan — stale-weight fleet sampling, periodic broadcast —
    with the PPO clipped-surrogate loss over V-trace advantages
    (policy_extra.APPOPolicy)."""


class SACTrainer(Trainer):
    """Discrete soft actor-critic over a replay buffer (reference:
    agents/sac/sac.py execution plan: store -> sample -> train)."""

    _policy_cls = None
    _default_config = {
        **COMMON_CONFIG,
        "policy_config": {},
        "buffer_size": 50_000,
        "learning_starts": 500,
        "sgd_batch_size": 64,
        "sgd_steps_per_iter": 8,
    }

    def __init__(self, config: Optional[dict] = None, env: Any = None):
        super().__init__(config, env)
        self.replay = ReplayBuffer(self.config["buffer_size"],
                                   self.config["seed"])

    def training_step(self) -> Dict[str, float]:
        return self._replay_step()


class DQNTrainer(Trainer):
    _policy_cls = DQNPolicy
    _default_config = {
        **COMMON_CONFIG,
        "policy_config": {},
        "buffer_size": 50_000,
        "learning_starts": 500,
        "sgd_batch_size": 64,
        "sgd_steps_per_iter": 16,
    }

    def __init__(self, config: Optional[dict] = None, env: Any = None):
        super().__init__(config, env)
        self.replay = ReplayBuffer(self.config["buffer_size"],
                                   self.config["seed"])

    def training_step(self) -> Dict[str, float]:
        return self._replay_step()


class PGTrainer(Trainer):
    """Vanilla policy gradient (reference: agents/pg/pg.py)."""

    _policy_cls = None
    _default_config = {**COMMON_CONFIG, "policy_config": {}}

    def training_step(self) -> Dict[str, float]:
        return self._onpolicy_step()


class DDPGTrainer(Trainer):
    """Continuous control over a replay buffer (reference:
    agents/ddpg/ddpg.py)."""

    _policy_cls = None
    _default_config = {
        **COMMON_CONFIG,
        "policy_config": {},
        "buffer_size": 50_000,
        "learning_starts": 500,
        "sgd_batch_size": 64,
        "sgd_steps_per_iter": 8,
    }

    def __init__(self, config: Optional[dict] = None, env: Any = None):
        super().__init__(config, env)
        self.replay = ReplayBuffer(self.config["buffer_size"],
                                   self.config["seed"])

    def training_step(self) -> Dict[str, float]:
        return self._replay_step()


class TD3Trainer(DDPGTrainer):
    """reference: agents/ddpg/td3.py"""

    _policy_cls = None


class SACContinuousTrainer(DDPGTrainer):
    """Continuous soft actor-critic over the replay plan (reference:
    agents/sac/sac.py — the continuous configuration; the discrete
    variant is SACTrainer)."""

    _policy_cls = None


class LinUCBTrainer(Trainer):
    """Contextual bandit, UCB exploration (reference:
    agents/bandit/bandit.py BanditLinUCBTrainer)."""

    _policy_cls = None
    _default_config = {**COMMON_CONFIG, "policy_config": {},
                       "rollout_fragment_length": 32,
                       "train_batch_size": 64}

    def training_step(self) -> Dict[str, float]:
        return self._onpolicy_step()


class LinTSTrainer(LinUCBTrainer):
    """reference: agents/bandit/bandit.py BanditLinTSTrainer"""

    _policy_cls = None


class MARWILTrainer(Trainer):
    """Offline RL: learns from a recorded experience file/batches, with
    on-policy evaluation through the worker fleet (reference:
    agents/marwil/marwil.py; config['input'] like rllib's offline input
    API). BC is the beta=0 special case."""

    _policy_cls = None
    _default_config = {
        **COMMON_CONFIG,
        "policy_config": {},
        "input": None,            # path to JSON lines or list of batches
        "sgd_steps_per_iter": 16,
        "evaluation_num_steps": 200,
    }

    def __init__(self, config: Optional[dict] = None, env: Any = None):
        super().__init__(config, env)
        from ray_tpu.rllib.offline import JsonReader

        if self.config["input"] is None:
            raise ValueError("offline trainers need config['input']")
        self.reader = JsonReader(self.config["input"])

    def training_step(self) -> Dict[str, float]:
        stats: Dict[str, float] = {}
        local = self.workers.local_worker
        for _ in range(self.config["sgd_steps_per_iter"]):
            batch = local.policy.postprocess_trajectory(self.reader.next())
            stats = local.learn_on_batch(batch)
            self._timesteps_total += batch.count
        self.workers.sync_weights()
        # on-policy evaluation drives the reward metric
        self.workers.sample_parallel(
            self._per_worker(self.config["evaluation_num_steps"]))
        return stats


class CQLTrainer(Trainer):
    """Offline continuous RL: conservative Q-learning over a recorded
    dataset (reference: agents/cql/cql.py — config['input'] like the
    offline API, SAC-style policy underneath). Evaluation is on-policy
    through the worker fleet."""

    _policy_cls = None
    _default_config = {
        **COMMON_CONFIG,
        "policy_config": {},
        "input": None,
        "sgd_batch_size": 64,
        "sgd_steps_per_iter": 32,
        "evaluation_num_steps": 200,
    }

    def __init__(self, config: Optional[dict] = None, env: Any = None):
        super().__init__(config, env)
        from ray_tpu.rllib.offline import JsonReader

        if self.config["input"] is None:
            raise ValueError("offline trainers need config['input']")
        reader = JsonReader(self.config["input"])
        # one dataset-wide replay pool sampled in minibatches
        self.replay = ReplayBuffer(
            capacity=sum(b.count for b in reader.batches),
            seed=self.config["seed"])
        for b in reader.batches:
            self.replay.add_batch(b)

    def training_step(self) -> Dict[str, float]:
        stats: Dict[str, float] = {}
        local = self.workers.local_worker
        for _ in range(self.config["sgd_steps_per_iter"]):
            stats = local.learn_on_batch(
                self.replay.sample(self.config["sgd_batch_size"]))
        self._timesteps_total += (self.config["sgd_steps_per_iter"]
                                  * self.config["sgd_batch_size"])
        self.workers.sync_weights()
        # on-policy evaluation drives the reward metric
        self.workers.sample_parallel(
            self._per_worker(self.config["evaluation_num_steps"]))
        return stats


class BCTrainer(MARWILTrainer):
    """Behavior cloning = MARWIL with beta=0 (reference:
    agents/marwil/bc.py)."""

    _policy_cls = None

    def __init__(self, config: Optional[dict] = None, env: Any = None):
        config = dict(config or {})
        pc = dict(config.get("policy_config", {}))
        pc["beta"] = 0.0
        config["policy_config"] = pc
        super().__init__(config, env)


# late binding: policy modules import Policy helpers from policy.py
from ray_tpu.rllib.policy_bandit import (  # noqa: E402
    LinTSPolicy,
    LinUCBPolicy,
)
from ray_tpu.rllib.policy_continuous import (  # noqa: E402
    ContinuousSACPolicy,
    CQLPolicy,
    DDPGPolicy,
    TD3Policy,
)
from ray_tpu.rllib.policy_extra import (  # noqa: E402
    A2CPolicy,
    APPOPolicy,
    IMPALAPolicy,
    SACPolicy,
)
from ray_tpu.rllib.policy_pg import (  # noqa: E402
    MARWILPolicy,
    PGPolicy,
)

A2CTrainer._policy_cls = A2CPolicy
IMPALATrainer._policy_cls = IMPALAPolicy
APPOTrainer._policy_cls = APPOPolicy
SACTrainer._policy_cls = SACPolicy
PGTrainer._policy_cls = PGPolicy
MARWILTrainer._policy_cls = MARWILPolicy
BCTrainer._policy_cls = MARWILPolicy
DDPGTrainer._policy_cls = DDPGPolicy
TD3Trainer._policy_cls = TD3Policy
SACContinuousTrainer._policy_cls = ContinuousSACPolicy
CQLTrainer._policy_cls = CQLPolicy
LinUCBTrainer._policy_cls = LinUCBPolicy
LinTSTrainer._policy_cls = LinTSPolicy
