"""Continuous-control JAX policies: DDPG and TD3.

Reference behavior: rllib/agents/ddpg/ (DDPG + the TD3 configuration:
twin critics, delayed policy updates, target policy smoothing —
ddpg/ddpg_tf_policy.py build_ddpg_models + td3.py). TPU-first idiom:
param pytrees, jit'd updates, polyak target averaging with jax.tree map.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.policy import Policy, init_mlp, mlp_apply
from ray_tpu.rllib.sample_batch import SampleBatch


def _polyak(target, online, tau: float):
    return jax.tree.map(lambda t, o: (1 - tau) * t + tau * o, target,
                        online)


class DDPGPolicy(Policy):
    """Deterministic actor + Q critic with target networks and Gaussian
    exploration noise."""

    twin_q = False
    policy_delay = 1
    smooth_target_policy = False

    def __init__(self, observation_dim: int, action_dim: int,
                 config: Optional[dict] = None):
        cfg = dict(actor_lr=1e-3, critic_lr=1e-3, gamma=0.99, tau=0.005,
                   noise_scale=0.1, target_noise=0.2, noise_clip=0.5,
                   actor_l2=1e-2, hidden=(64, 64), seed=0,
                   action_low=-1.0, action_high=1.0)
        cfg.update(config or {})
        self.cfg = cfg
        self.action_dim = action_dim
        low = float(np.min(np.asarray(cfg["action_low"])))
        high = float(np.max(np.asarray(cfg["action_high"])))
        self._scale = (high - low) / 2.0
        self._mid = (high + low) / 2.0
        hidden = tuple(cfg["hidden"])
        key = jax.random.PRNGKey(cfg["seed"])
        ka, k1, k2 = jax.random.split(key, 3)
        self.params = {
            "actor": init_mlp(ka, (observation_dim, *hidden, action_dim)),
            "q1": init_mlp(k1, (observation_dim + action_dim, *hidden, 1)),
        }
        if self.twin_q:
            self.params["q2"] = init_mlp(
                k2, (observation_dim + action_dim, *hidden, 1))
        self.target = jax.tree.map(lambda x: x, self.params)
        self.actor_opt = optax.adam(cfg["actor_lr"])
        self.critic_opt = optax.adam(cfg["critic_lr"])
        critic_keys = [k for k in self.params if k.startswith("q")]
        self.actor_state = self.actor_opt.init(self.params["actor"])
        self.critic_state = self.critic_opt.init(
            {k: self.params[k] for k in critic_keys})
        self._rng = np.random.default_rng(cfg["seed"])
        self._updates = 0
        scale, mid = self._scale, self._mid
        twin, smooth = self.twin_q, self.smooth_target_policy

        def _act(params, obs):
            return jnp.tanh(mlp_apply(params["actor"], obs)) * scale + mid

        def _q(params, name, obs, act):
            return mlp_apply(params[name],
                             jnp.concatenate([obs, act], axis=1))[..., 0]

        @jax.jit
        def _forward(params, obs):
            return _act(params, obs)

        @jax.jit
        def _critic_update(params, target, critic_state, obs, actions,
                           rewards, dones, next_obs, noise):
            next_a = _act(target, next_obs)
            if smooth:  # TD3 target policy smoothing
                next_a = jnp.clip(next_a + noise, mid - scale,
                                  mid + scale)
            q_next = _q(target, "q1", next_obs, next_a)
            if twin:
                q_next = jnp.minimum(q_next,
                                     _q(target, "q2", next_obs, next_a))
            y = rewards + cfg["gamma"] * (1.0 - dones) * q_next
            y = jax.lax.stop_gradient(y)
            ckeys = ["q1", "q2"] if twin else ["q1"]

            def loss_fn(critics):
                p = {**params, **critics}
                loss = jnp.mean((_q(p, "q1", obs, actions) - y) ** 2)
                if twin:
                    loss = loss + jnp.mean(
                        (_q(p, "q2", obs, actions) - y) ** 2)
                return loss

            critics = {k: params[k] for k in ckeys}
            loss, grads = jax.value_and_grad(loss_fn)(critics)
            updates, critic_state = self.critic_opt.update(
                grads, critic_state, critics)
            critics = optax.apply_updates(critics, updates)
            return {**params, **critics}, critic_state, loss

        @jax.jit
        def _actor_update(params, actor_state, obs):
            def loss_fn(actor):
                p = {**params, "actor": actor}
                raw = mlp_apply(actor, obs)
                # pre-tanh L2 keeps the actor out of tanh saturation
                # while the critic is still settling (the reference's
                # l2_reg serves the same purpose, ddpg_tf_policy.py)
                return (-jnp.mean(_q(p, "q1", obs,
                                     jnp.tanh(raw) * scale + mid))
                        + cfg["actor_l2"] * jnp.mean(raw ** 2))

            loss, grads = jax.value_and_grad(loss_fn)(params["actor"])
            updates, actor_state = self.actor_opt.update(
                grads, actor_state, params["actor"])
            actor = optax.apply_updates(params["actor"], updates)
            return {**params, "actor": actor}, actor_state, loss

        @jax.jit
        def _sync_targets(target, params):
            return _polyak(target, params, cfg["tau"])

        self._forward = _forward
        self._critic_update = _critic_update
        self._actor_update = _actor_update
        self._sync_targets = _sync_targets

    def compute_actions(self, obs) -> Tuple[np.ndarray, dict]:
        obs = np.atleast_2d(np.asarray(obs, np.float32))
        act = np.asarray(self._forward(self.params, obs))
        act = act + self._rng.normal(
            scale=self.cfg["noise_scale"] * self._scale, size=act.shape)
        low = self._mid - self._scale
        high = self._mid + self._scale
        return np.clip(act, low, high).astype(np.float32), {}

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        obs = jnp.asarray(np.asarray(batch[sb.OBS], np.float32))
        acts = np.asarray(batch[sb.ACTIONS], np.float32)
        if acts.ndim == 1:
            acts = acts[:, None]
        noise = np.clip(
            self._rng.normal(scale=self.cfg["target_noise"],
                             size=(len(acts), self.action_dim)),
            -self.cfg["noise_clip"], self.cfg["noise_clip"])
        self.params, self.critic_state, q_loss = self._critic_update(
            self.params, self.target, self.critic_state, obs,
            jnp.asarray(acts),
            jnp.asarray(np.asarray(batch[sb.REWARDS], np.float32)),
            jnp.asarray(np.asarray(batch[sb.DONES], np.float32)),
            jnp.asarray(np.asarray(batch[sb.NEXT_OBS], np.float32)),
            jnp.asarray(noise, jnp.float32))
        stats = {"critic_loss": float(q_loss)}
        self._updates += 1
        if self._updates % self.policy_delay == 0:  # TD3 delayed actor
            self.params, self.actor_state, a_loss = self._actor_update(
                self.params, self.actor_state, obs)
            self.target = self._sync_targets(self.target, self.params)
            stats["actor_loss"] = float(a_loss)
        return stats

    def get_weights(self):
        return jax.device_get({"params": self.params,
                               "target": self.target})

    def set_weights(self, weights) -> None:
        self.params = jax.device_put(weights["params"])
        self.target = jax.device_put(weights["target"])


class TD3Policy(DDPGPolicy):
    """TD3 = DDPG + twin critics + delayed policy updates + target
    policy smoothing (reference: agents/ddpg/td3.py TD3_DEFAULT_CONFIG)."""

    twin_q = True
    policy_delay = 2
    smooth_target_policy = True


class ContinuousSACPolicy(Policy):
    """Soft actor-critic for continuous actions: squashed-Gaussian
    actor (reparameterized), twin soft-Q critics, learned temperature
    against a -action_dim entropy target (reference: agents/sac/
    sac_tf_policy.py — the continuous configuration; the discrete
    variant lives in policy_extra.SACPolicy).

    Subclasses extend the critic loss through `_build_update(penalty_fn)`
    (CQL adds its conservative penalty there) so the squashed-Gaussian
    math lives in exactly one place."""

    LOG_STD_MIN = -10.0
    LOG_STD_MAX = 2.0
    _report_penalty = False  # CQL reports its conservative penalty

    def __init__(self, observation_dim: int, action_dim: int,
                 config: Optional[dict] = None):
        cfg = dict(actor_lr=3e-4, critic_lr=3e-4, alpha_lr=3e-4,
                   gamma=0.99, tau=0.005, hidden=(64, 64), seed=0,
                   init_alpha=0.1, action_low=-1.0, action_high=1.0)
        cfg.update(config or {})
        self.cfg = cfg
        self.action_dim = action_dim
        low = float(np.min(np.asarray(cfg["action_low"])))
        high = float(np.max(np.asarray(cfg["action_high"])))
        scale = (high - low) / 2.0
        mid = (high + low) / 2.0
        self._scale, self._mid = scale, mid
        hidden = tuple(cfg["hidden"])
        key = jax.random.PRNGKey(cfg["seed"])
        ka, k1, k2 = jax.random.split(key, 3)
        self.params = {
            # actor emits mean and log_std
            "actor": init_mlp(ka, (observation_dim, *hidden,
                                   2 * action_dim)),
            "q1": init_mlp(k1, (observation_dim + action_dim, *hidden, 1)),
            "q2": init_mlp(k2, (observation_dim + action_dim, *hidden, 1)),
            "log_alpha": jnp.log(jnp.float32(cfg["init_alpha"])),
        }
        self.target = jax.tree.map(lambda x: x, self.params)
        # one combined loss, PER-COMPONENT learning rates
        self.opt = optax.multi_transform(
            {"actor": optax.adam(cfg["actor_lr"]),
             "critic": optax.adam(cfg["critic_lr"]),
             "alpha": optax.adam(cfg["alpha_lr"])},
            {"actor": "actor", "q1": "critic", "q2": "critic",
             "log_alpha": "alpha"})
        self.opt_state = self.opt.init(self.params)
        self._key = jax.random.PRNGKey(cfg["seed"] + 1)
        target_entropy = -float(action_dim)
        gamma, tau = cfg["gamma"], cfg["tau"]

        def actor_dist(params, obs):
            out = mlp_apply(params["actor"], obs)
            mean, log_std = jnp.split(out, 2, axis=-1)
            log_std = jnp.clip(log_std, self.LOG_STD_MIN,
                               self.LOG_STD_MAX)
            return mean, log_std

        def sample_action(params, obs, key):
            mean, log_std = actor_dist(params, obs)
            std = jnp.exp(log_std)
            eps = jax.random.normal(key, mean.shape)
            pre_tanh = mean + std * eps
            a = jnp.tanh(pre_tanh)
            # change-of-variables log-prob: tanh squash AND the affine
            # rescale to the action range (each contributes a Jacobian)
            logp = (-0.5 * (eps ** 2 + 2 * log_std
                            + jnp.log(2 * jnp.pi))
                    - jnp.log(jnp.maximum(1 - a ** 2, 1e-6))
                    - jnp.log(scale))
            return a * scale + mid, jnp.sum(logp, axis=-1)

        def q(params, name, obs, act):
            return mlp_apply(params[name],
                             jnp.concatenate([obs, act], axis=1))[..., 0]

        # exposed for subclasses (CQL builds its penalty on these)
        self._sac_helpers = (actor_dist, sample_action, q)

        @jax.jit
        def _sample(params, obs, key):
            return sample_action(params, obs, key)[0]

        @jax.jit
        def _mean_action(params, obs):
            mean, _ = actor_dist(params, obs)
            return jnp.tanh(mean) * scale + mid

        def build_update(penalty_fn=None):
            """penalty_fn(params, obs, actions, key) -> scalar added to
            the combined loss (the CQL hook); None -> plain SAC."""

            @jax.jit
            def _update(params, target, opt_state, obs, actions, rewards,
                        dones, next_obs, key):
                k1, k2, k3 = jax.random.split(key, 3)
                alpha = jnp.exp(params["log_alpha"])
                next_a, next_logp = sample_action(params, next_obs, k1)
                q_next = jnp.minimum(q(target, "q1", next_obs, next_a),
                                     q(target, "q2", next_obs, next_a))
                y = rewards + gamma * (1.0 - dones) * (
                    q_next - alpha * next_logp)
                y = jax.lax.stop_gradient(y)

                def loss_fn(p):
                    q1 = q(p, "q1", obs, actions)
                    q2 = q(p, "q2", obs, actions)
                    critic_loss = jnp.mean((q1 - y) ** 2) + jnp.mean(
                        (q2 - y) ** 2)
                    penalty = (jnp.float32(0.0) if penalty_fn is None
                               else penalty_fn(p, obs, actions, k3))
                    a, logp = sample_action(p, obs, k2)
                    q_pi = jnp.minimum(
                        q(jax.lax.stop_gradient(p), "q1", obs, a),
                        q(jax.lax.stop_gradient(p), "q2", obs, a))
                    alpha_live = jnp.exp(p["log_alpha"])
                    actor_loss = jnp.mean(
                        jax.lax.stop_gradient(alpha_live) * logp - q_pi)
                    alpha_loss = -jnp.mean(
                        p["log_alpha"] * jax.lax.stop_gradient(
                            logp + target_entropy))
                    total = (critic_loss + penalty + actor_loss
                             + alpha_loss)
                    return total, (critic_loss, actor_loss, alpha_live,
                                   penalty)

                grads, aux = jax.grad(loss_fn, has_aux=True)(params)
                updates, opt_state = self.opt.update(grads, opt_state,
                                                     params)
                params = optax.apply_updates(params, updates)
                return params, _polyak(target, params, tau), opt_state, aux

            return _update

        self._build_update = build_update
        self._sample_fn = _sample
        self._mean_fn = _mean_action
        self._update_fn = build_update()

    def compute_actions(self, obs) -> Tuple[np.ndarray, dict]:
        obs = np.atleast_2d(np.asarray(obs, np.float32))
        self._key, sub = jax.random.split(self._key)
        return np.asarray(self._sample_fn(self.params, obs, sub)), {}

    def greedy_actions(self, obs) -> np.ndarray:
        obs = np.atleast_2d(np.asarray(obs, np.float32))
        return np.asarray(self._mean_fn(self.params, obs))

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        acts = np.asarray(batch[sb.ACTIONS], np.float32)
        if acts.ndim == 1:
            acts = acts[:, None]
        self._key, sub = jax.random.split(self._key)
        self.params, self.target, self.opt_state, aux = self._update_fn(
            self.params, self.target, self.opt_state,
            jnp.asarray(np.asarray(batch[sb.OBS], np.float32)),
            jnp.asarray(acts),
            jnp.asarray(np.asarray(batch[sb.REWARDS], np.float32)),
            jnp.asarray(np.asarray(batch[sb.DONES], np.float32)),
            jnp.asarray(np.asarray(batch[sb.NEXT_OBS], np.float32)),
            sub)
        stats = {"critic_loss": float(aux[0]),
                 "actor_loss": float(aux[1]),
                 "alpha": float(aux[2])}
        if self._report_penalty:  # keyed on policy TYPE, not value —
            #                       a zero-weight CQL ablation still
            #                       reports its (zero) penalty
            stats["cql_penalty"] = float(aux[3])
        return stats

    def get_weights(self):
        return jax.device_get({"params": self.params,
                               "target": self.target})

    def set_weights(self, weights) -> None:
        self.params = jax.device_put(weights["params"])
        self.target = jax.device_put(weights["target"])


class CQLPolicy(ContinuousSACPolicy):
    """Conservative Q-learning for OFFLINE continuous control
    (reference: agents/cql/cql.py over the SAC policy): the combined
    loss adds min_q_weight * (logsumexp_a Q(s,a) - Q(s, a_data)),
    pushing Q down on out-of-distribution actions so the actor cannot
    exploit overestimated unseen actions in a static dataset. Everything
    else — the squashed-Gaussian math, targets, temperature — is the
    parent's, reused through the penalty hook."""

    _report_penalty = True

    def __init__(self, observation_dim: int, action_dim: int,
                 config: Optional[dict] = None):
        cfg = dict(min_q_weight=1.0, num_cql_actions=8)
        cfg.update(config or {})
        super().__init__(observation_dim, action_dim, cfg)
        cfg = self.cfg
        n_rand = cfg["num_cql_actions"]
        weight = cfg["min_q_weight"]
        scale, mid = self._scale, self._mid
        _, _, q = self._sac_helpers

        def q_many(params, name, obs, acts):
            """obs [B, O], acts [B, N, A] -> [B, N]."""
            b, n, _ = acts.shape
            obs_rep = jnp.repeat(obs, n, axis=0)
            flat = q(params, name, obs_rep, acts.reshape(b * n, -1))
            return flat.reshape(b, n)

        def penalty_fn(p, obs, actions, key):
            b = obs.shape[0]
            rand_actions = jax.random.uniform(
                key, (b, n_rand, actions.shape[-1]),
                minval=mid - scale, maxval=mid + scale)
            penalty = jnp.float32(0.0)
            for name in ("q1", "q2"):
                ood = q_many(p, name, obs, rand_actions)
                q_data = q(p, name, obs, actions)
                penalty = penalty + jnp.mean(
                    jax.scipy.special.logsumexp(ood, axis=1) - q_data)
            return weight * penalty

        self._update_fn = self._build_update(penalty_fn)
