"""Contextual bandit policies: LinUCB and linear Thompson sampling.

Reference behavior: rllib/agents/bandit/ (BanditLinUCBTrainer,
BanditLinTSTrainer over rllib/agents/bandit/bandit_tf_policy.py's
per-arm linear models). Pure linear algebra — numpy is the right tool;
the batched update uses one solve per arm.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.policy import Policy
from ray_tpu.rllib.sample_batch import SampleBatch


class LinUCBPolicy(Policy):
    """Per-arm ridge regression with an upper confidence bonus:
    score_a = theta_a.x + alpha * sqrt(x' A_a^-1 x)."""

    thompson = False

    def __init__(self, observation_dim: int, num_actions: int,
                 config: Optional[dict] = None):
        cfg = dict(alpha=1.0, lam=1.0, ts_scale=1.0, seed=0)
        cfg.update(config or {})
        self.cfg = cfg
        self.d = observation_dim
        self.k = num_actions
        self.A = np.stack([np.eye(self.d) * cfg["lam"]
                           for _ in range(self.k)])   # [K, d, d]
        self.b = np.zeros((self.k, self.d))            # [K, d]
        self._rng = np.random.default_rng(cfg["seed"])

    def _theta(self) -> np.ndarray:
        return np.stack([np.linalg.solve(self.A[a], self.b[a])
                         for a in range(self.k)])      # [K, d]

    def compute_actions(self, obs) -> Tuple[np.ndarray, dict]:
        x = np.atleast_2d(np.asarray(obs, np.float64))  # [B, d]
        theta = self._theta()
        mean = x @ theta.T                               # [B, K]
        inv = np.stack([np.linalg.inv(self.A[a])
                        for a in range(self.k)])         # [K, d, d]
        # sigma[b, a] = sqrt(x_b' A_a^-1 x_b)
        sigma = np.sqrt(np.einsum("bd,ade,be->ba", x, inv, x))
        if self.thompson:
            # sample theta_a ~ N(theta_a, ts_scale^2 A_a^-1) per decision
            scores = mean + self.cfg["ts_scale"] * sigma \
                * self._rng.standard_normal(mean.shape)
        else:
            scores = mean + self.cfg["alpha"] * sigma
        return np.argmax(scores, axis=1), {}

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        x = np.atleast_2d(np.asarray(batch[sb.OBS], np.float64))
        actions = np.asarray(batch[sb.ACTIONS], np.int64)
        rewards = np.asarray(batch[sb.REWARDS], np.float64)
        for a in range(self.k):
            mask = actions == a
            if not mask.any():
                continue
            xa = x[mask]
            self.A[a] += xa.T @ xa
            self.b[a] += rewards[mask] @ xa
        theta = self._theta()
        pred = np.einsum("bd,bd->b", x, theta[actions])
        return {"mse": float(np.mean((pred - rewards) ** 2)),
                "pulls": int(len(actions))}

    def get_weights(self):
        return {"A": self.A.copy(), "b": self.b.copy()}

    def set_weights(self, weights) -> None:
        self.A = np.asarray(weights["A"]).copy()
        self.b = np.asarray(weights["b"]).copy()


class LinTSPolicy(LinUCBPolicy):
    """Linear Thompson sampling — same sufficient statistics, draws from
    the posterior instead of adding a UCB bonus."""

    thompson = True
