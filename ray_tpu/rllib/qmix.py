"""QMIX / VDN: cooperative multi-agent Q-learning with value
decomposition.

Reference behavior: rllib/agents/qmix/ (QMixTrainer, qmix_policy.py's
monotonic mixing network over agent Qs + global state; VDN is the
additive special case). JAX idiom like the rest of the stack: param
pytrees, jit'd TD updates, polyak-free hard target sync.

The team trains on JOINT transitions (every agent's obs/action plus the
shared reward), so this trainer samples its own joint replay buffer
rather than the per-policy batches of MultiAgentTrainer.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.multi_agent import MultiAgentEnv
from ray_tpu.rllib.policy import init_mlp, mlp_apply


class TwoStepCoopEnv(MultiAgentEnv):
    """The QMIX paper's two-step cooperative game: agent a0's first
    action selects the second-step payoff matrix; in state 2 the optimal
    joint action pays 8 but miscoordination pays 0/1 — independent
    learners settle for the safe 7, value decomposition finds 8."""

    agent_ids = ("a0", "a1")
    observation_dim = 3  # one-hot state id
    num_actions = 2

    def __init__(self, seed: Optional[int] = None):
        # the game is fully deterministic; seed accepted for registry
        # compatibility with the other envs' constructors
        del seed
        self._state = 0

    def _obs(self) -> Dict[str, np.ndarray]:
        one_hot = np.zeros(3, np.float32)
        one_hot[self._state] = 1.0
        return {aid: one_hot.copy() for aid in self.agent_ids}

    def reset(self) -> Dict[str, np.ndarray]:
        self._state = 0
        return self._obs()

    def step(self, actions: Dict[str, int]):
        if self._state == 0:
            self._state = 1 if int(actions["a0"]) == 0 else 2
            rewards = {aid: 0.0 for aid in self.agent_ids}
            dones = {aid: False for aid in self.agent_ids}
            dones["__all__"] = False
            return self._obs(), rewards, dones, {a: {} for a
                                                 in self.agent_ids}
        if self._state == 1:
            team = 7.0
        else:  # state 2: [[0, 1], [1, 8]]
            matrix = ((0.0, 1.0), (1.0, 8.0))
            team = matrix[int(actions["a0"])][int(actions["a1"])]
        rewards = {aid: team for aid in self.agent_ids}
        dones = {aid: True for aid in self.agent_ids}
        dones["__all__"] = True
        return self.reset(), rewards, dones, {a: {} for a
                                              in self.agent_ids}


class _JointReplay:
    """FIFO replay of joint transitions, rows of
    (obs[n_agents], actions[n_agents], team_reward, done, next_obs);
    the global state is derived at sample time by flattening obs."""

    def __init__(self, capacity: int, seed: int):
        self.capacity = capacity
        self._rows: List[tuple] = []
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def add(self, row: tuple) -> None:
        if len(self._rows) < self.capacity:
            self._rows.append(row)
        else:
            self._rows[self._next] = row
        self._next = (self._next + 1) % self.capacity

    def __len__(self) -> int:
        return len(self._rows)

    def sample(self, n: int) -> List[tuple]:
        idx = self._rng.integers(len(self._rows), size=n)
        return [self._rows[i] for i in idx]


class QMixTrainer:
    """Centralized training, decentralized execution. config['mixer']:
    'qmix' (monotonic state-conditioned mixer, the default) or 'vdn'
    (plain sum — reference: qmix.py's mixer config)."""

    def __init__(self, config: Optional[dict] = None, env: Any = None):
        cfg = {
            "env": None,
            "env_config": {},
            "mixer": "qmix",
            "lr": 5e-3,
            "gamma": 0.99,
            "hidden": (32,),
            "mixer_hidden": 16,
            "buffer_size": 5000,
            "sgd_batch_size": 64,
            "sgd_steps_per_iter": 32,
            "rollout_steps_per_iter": 128,
            "target_update_freq": 50,
            "epsilon": 1.0,
            "epsilon_min": 0.05,
            "epsilon_decay": 0.995,
            "seed": 0,
        }
        cfg.update(config or {})
        if env is not None:
            cfg["env"] = env
        if cfg["env"] is None:
            raise ValueError("config['env'] is required")
        self.config = cfg
        env_cls = cfg["env"]
        self.env: MultiAgentEnv = (
            env_cls(**cfg["env_config"]) if isinstance(env_cls, type)
            else env_cls)
        self.agent_ids = tuple(self.env.agent_ids)
        self.n_agents = len(self.agent_ids)
        obs_dim = self.env.observation_dim
        self.n_actions = self.env.num_actions
        state_dim = obs_dim * self.n_agents
        hidden = tuple(cfg["hidden"])
        mh = cfg["mixer_hidden"]
        key = jax.random.PRNGKey(cfg["seed"])
        kq, k1, k2, k3, k4 = jax.random.split(key, 5)
        # one shared per-agent Q network (parameter sharing, the
        # reference default) + the state-conditioned mixer hypernet
        self.params = {
            "q": init_mlp(kq, (obs_dim, *hidden, self.n_actions)),
            "hyper_w1": init_mlp(k1, (state_dim, self.n_agents * mh)),
            "hyper_b1": init_mlp(k2, (state_dim, mh)),
            "hyper_w2": init_mlp(k3, (state_dim, mh)),
            "hyper_b2": init_mlp(k4, (state_dim, 1)),
        }
        self.target = jax.tree.map(lambda x: x, self.params)
        self.opt = optax.adam(cfg["lr"])
        self.opt_state = self.opt.init(self.params)
        self._rng = np.random.default_rng(cfg["seed"])
        self.replay = _JointReplay(cfg["buffer_size"], cfg["seed"])
        self.epsilon = cfg["epsilon"]
        self._updates = 0
        self._iteration = 0
        self.episode_rewards: List[float] = []
        self._rollout_obs: Optional[Dict[str, np.ndarray]] = None
        self._ep_reward = 0.0
        mixer = cfg["mixer"]
        gamma = cfg["gamma"]
        n_agents = self.n_agents

        def q_values(params, obs):                 # [B, n_agents, obs]
            return mlp_apply(params["q"], obs)     # [B, n_agents, A]

        def mix(params, agent_qs, state):
            """Monotonic mixing: abs() on hypernet weights keeps
            dQ_tot/dQ_i >= 0 (reference: qmix_policy.py Mixer)."""
            if mixer == "vdn":
                return jnp.sum(agent_qs, axis=-1)           # [B]
            b = agent_qs.shape[0]
            w1 = jnp.abs(mlp_apply(params["hyper_w1"], state)).reshape(
                b, n_agents, mh)
            b1 = mlp_apply(params["hyper_b1"], state)        # [B, mh]
            hidden_q = jax.nn.elu(
                jnp.einsum("ba,bam->bm", agent_qs, w1) + b1)
            w2 = jnp.abs(mlp_apply(params["hyper_w2"], state))  # [B, mh]
            b2 = mlp_apply(params["hyper_b2"], state)[..., 0]   # [B]
            return jnp.einsum("bm,bm->b", hidden_q, w2) + b2

        @jax.jit
        def _update(params, target, opt_state, obs, actions, rewards,
                    dones, next_obs, state, next_state):
            q_next = q_values(target, next_obs)               # [B,N,A]
            best_next = jnp.max(q_next, axis=-1)              # [B,N]
            y = rewards + gamma * (1.0 - dones) * mix(
                target, best_next, next_state)  # target params: constant
                #                                 w.r.t. the grads below

            def loss_fn(p):
                qs = q_values(p, obs)                         # [B,N,A]
                chosen = jnp.take_along_axis(
                    qs, actions[..., None], axis=-1)[..., 0]  # [B,N]
                q_tot = mix(p, chosen, state)                 # [B]
                return jnp.mean((q_tot - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self.opt.update(grads, opt_state,
                                                 params)
            return optax.apply_updates(params, updates), opt_state, loss

        @jax.jit
        def _greedy(params, obs):                 # [N, obs] -> [N]
            return jnp.argmax(mlp_apply(params["q"], obs), axis=-1)

        self._update = _update
        self._greedy = _greedy

    # ------------------------------------------------------------ rollouts
    def _act(self, obs: Dict[str, np.ndarray]) -> Dict[str, int]:
        out = self.greedy_actions(obs)
        for aid in self.agent_ids:  # epsilon-greedy over the greedy base
            if self._rng.random() < self.epsilon:
                out[aid] = int(self._rng.integers(self.n_actions))
        return out

    def _rollout(self, steps: int) -> None:
        # episode state persists ACROSS training steps: an env whose
        # episodes outlast one rollout window must keep its in-flight
        # episode (and its reward tally), not abandon it at a reset
        if self._rollout_obs is None:
            self._rollout_obs = self.env.reset()
        obs = self._rollout_obs
        for _ in range(steps):
            actions = self._act(obs)
            next_obs, rewards, dones, _ = self.env.step(actions)
            team = float(np.mean(list(rewards.values())))
            self._ep_reward += team
            done = bool(dones.get("__all__", False))
            self.replay.add((
                np.stack([obs[a] for a in self.agent_ids]),
                np.array([actions[a] for a in self.agent_ids], np.int32),
                team, float(done),
                np.stack([next_obs[a] for a in self.agent_ids]),
            ))
            if done:
                self.episode_rewards.append(self._ep_reward)
                self._ep_reward = 0.0
                obs = self.env.reset()
            else:
                obs = next_obs
            self.epsilon = max(self.config["epsilon_min"],
                               self.epsilon * self.config["epsilon_decay"])
        self._rollout_obs = obs

    # ------------------------------------------------------------- training
    def training_step(self) -> Dict[str, float]:
        self._rollout(self.config["rollout_steps_per_iter"])
        if len(self.replay) < self.config["sgd_batch_size"]:
            return {}
        loss = 0.0
        for _ in range(self.config["sgd_steps_per_iter"]):
            rows = self.replay.sample(self.config["sgd_batch_size"])
            obs = jnp.asarray(np.stack([r[0] for r in rows]))
            actions = jnp.asarray(np.stack([r[1] for r in rows]))
            rewards = jnp.asarray(np.array([r[2] for r in rows],
                                           np.float32))
            dones = jnp.asarray(np.array([r[3] for r in rows],
                                         np.float32))
            next_obs = jnp.asarray(np.stack([r[4] for r in rows]))
            state = obs.reshape(obs.shape[0], -1)
            next_state = next_obs.reshape(next_obs.shape[0], -1)
            self.params, self.opt_state, loss = self._update(
                self.params, self.target, self.opt_state, obs, actions,
                rewards, dones, next_obs, state, next_state)
            self._updates += 1
            if self._updates % self.config["target_update_freq"] == 0:
                self.target = jax.tree.map(lambda x: x, self.params)
        return {"td_loss": float(loss), "epsilon": self.epsilon}

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        stats = self.training_step()
        self._iteration += 1
        rewards = self.episode_rewards[-100:]
        return {
            "training_iteration": self._iteration,
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else float("nan"),
            "time_this_iter_s": time.perf_counter() - t0,
            "info": {"learner": stats},
        }

    def greedy_actions(self, obs: Dict[str, np.ndarray]) -> Dict[str, int]:
        stacked = np.stack([obs[a] for a in self.agent_ids])
        greedy = np.asarray(self._greedy(self.params, stacked))
        return {aid: int(greedy[i])
                for i, aid in enumerate(self.agent_ids)}

    def save_checkpoint(self) -> dict:
        return {"params": jax.device_get(self.params),
                "iteration": self._iteration}

    def restore(self, checkpoint: dict) -> None:
        self.params = jax.device_put(checkpoint["params"])
        self.target = jax.tree.map(lambda x: x, self.params)
        self._iteration = checkpoint["iteration"]

    def stop(self) -> None:
        pass


class VDNTrainer(QMixTrainer):
    """Additive value decomposition (reference: mixer='vdn')."""

    def __init__(self, config: Optional[dict] = None, env: Any = None):
        config = dict(config or {})
        config["mixer"] = "vdn"
        super().__init__(config, env)
