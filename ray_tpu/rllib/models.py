"""Model catalog — network families for RL policies.

Reference: rllib/models/ (ModelCatalog + TF/Torch FCNet, VisionNet,
RNN wrappers). The TPU build ships pure-JAX functional models chosen by
observation shape, exactly how the reference's catalog dispatches:

  - fully-connected (FCNet)              flat observations
  - convolutional (VisionNetwork)        image observations [H, W, C]
  - recurrent (GRU wrapper)              sequence policies (lax.scan —
                                         compiler-friendly recurrence,
                                         no Python loops under jit)

Every model is an (init(key) -> params, apply(params, x) -> out) pair so
policies stay framework-free and jit/vmap/pjit-composable.
"""

from __future__ import annotations

from typing import Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

Model = Tuple[Callable, Callable]  # (init, apply)


# ------------------------------------------------------------------ dense
def fcnet(sizes: Sequence[int], activation=jax.nn.tanh) -> Model:
    """FCNet (reference: rllib/models/tf/fcnet.py)."""

    def init(key):
        params = []
        for din, dout in zip(sizes[:-1], sizes[1:]):
            key, sub = jax.random.split(key)
            w = jax.random.normal(sub, (din, dout)) * jnp.sqrt(2.0 / din)
            params.append({"w": w, "b": jnp.zeros(dout)})
        return params

    def apply(params, x):
        for i, layer in enumerate(params):
            x = x @ layer["w"] + layer["b"]
            if i < len(params) - 1:
                x = activation(x)
        return x

    return init, apply


# ------------------------------------------------------------------- conv
def vision_net(input_shape: Tuple[int, int, int], num_outputs: int,
               filters: Sequence[Tuple[int, int, int]] = (
                   (16, 4, 2), (32, 4, 2), (64, 3, 1)),
               hidden: int = 256) -> Model:
    """VisionNetwork (reference: rllib/models/tf/visionnet.py): conv
    stack then dense head. Convs map onto the MXU; NHWC layout."""

    def init(key):
        h, w, c_in = input_shape
        params = {"convs": []}
        for c_out, k, s in filters:
            key, sub = jax.random.split(key)
            fan_in = k * k * c_in
            params["convs"].append({
                "w": jax.random.normal(sub, (k, k, c_in, c_out))
                * jnp.sqrt(2.0 / fan_in),
                "b": jnp.zeros(c_out),
            })
            h = -(-h // s)
            w = -(-w // s)
            c_in = c_out
        flat = h * w * c_in
        key, k1, k2 = jax.random.split(key, 3)
        params["fc"] = {
            "w": jax.random.normal(k1, (flat, hidden))
            * jnp.sqrt(2.0 / flat),
            "b": jnp.zeros(hidden),
        }
        params["head"] = {
            "w": jax.random.normal(k2, (hidden, num_outputs))
            * jnp.sqrt(2.0 / hidden),
            "b": jnp.zeros(num_outputs),
        }
        return params

    strides = [s for _c, _k, s in filters]  # static, not part of the pytree

    def apply(params, x):
        # x: [B, H, W, C] float
        for conv, stride in zip(params["convs"], strides):
            x = jax.lax.conv_general_dilated(
                x, conv["w"],
                window_strides=(stride, stride),
                padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = jax.nn.relu(x + conv["b"])
        x = x.reshape(x.shape[0], -1)
        x = jax.nn.relu(x @ params["fc"]["w"] + params["fc"]["b"])
        return x @ params["head"]["w"] + params["head"]["b"]

    return init, apply


# -------------------------------------------------------------- recurrent
def gru_net(input_dim: int, hidden: int, num_outputs: int) -> Model:
    """Recurrent policy net (reference: rllib/models/tf/recurrent_net.py).
    The sequence recurrence is a lax.scan — static-shape, fusable, no
    Python-level loop under jit."""

    def init(key):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        scale_x = jnp.sqrt(1.0 / input_dim)
        scale_h = jnp.sqrt(1.0 / hidden)
        return {
            "wx": jax.random.normal(k1, (input_dim, 3 * hidden)) * scale_x,
            "wh": jax.random.normal(k2, (hidden, 3 * hidden)) * scale_h,
            "b": jnp.zeros(3 * hidden),
            "head_w": jax.random.normal(k3, (hidden, num_outputs))
            * scale_h,
            "head_b": jnp.zeros(num_outputs),
            "h0": jnp.zeros(hidden),
        }

    def cell(params, h, x):
        gates_x = x @ params["wx"]
        gates_h = h @ params["wh"]
        xr, xz, xn = jnp.split(gates_x + params["b"], 3, axis=-1)
        hr, hz, hn = jnp.split(gates_h, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        return (1 - z) * n + z * h

    def apply(params, x, h_init=None):
        # x: [B, T, D] -> (outputs [B, T, O], final hidden [B, H])
        batch = x.shape[0]
        h = (jnp.broadcast_to(params["h0"], (batch, params["h0"].shape[0]))
             if h_init is None else h_init)

        def scan_step(h, xt):
            h = cell(params, h, xt)
            return h, h @ params["head_w"] + params["head_b"]

        h_final, outs = jax.lax.scan(scan_step, h,
                                     jnp.swapaxes(x, 0, 1))
        return jnp.swapaxes(outs, 0, 1), h_final

    return init, apply


# ----------------------------------------------------------------- catalog
class ModelCatalog:
    """Pick a model family from the observation shape (reference:
    rllib/models/catalog.py ModelCatalog.get_model_v2)."""

    @staticmethod
    def get_model(obs_shape: Tuple[int, ...], num_outputs: int,
                  config: Dict = None) -> Model:
        config = config or {}
        if len(obs_shape) == 3:
            return vision_net(obs_shape, num_outputs,
                              filters=config.get(
                                  "conv_filters",
                                  ((16, 4, 2), (32, 4, 2), (64, 3, 1))),
                              hidden=config.get("post_fcnet_hiddens", 256))
        if config.get("use_rnn"):
            return gru_net(obs_shape[0],
                           config.get("rnn_hidden", 128), num_outputs)
        hiddens = tuple(config.get("fcnet_hiddens", (64, 64)))
        return fcnet((obs_shape[0], *hiddens, num_outputs))
