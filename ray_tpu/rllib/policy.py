"""JAX policies: PPO (clipped surrogate + GAE) and DQN (double-Q TD).

Reference: rllib/policy/ + rllib/agents/{ppo,dqn}/ *behavior* —
re-designed for TPU idiom: pure-functional param pytrees, jit'd
action/update steps with static shapes, optax optimizers. Every policy
is a pair of jitted functions over a params pytree, so the same code
runs per-chip under pmap/pjit when fleets scale up.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.sample_batch import SampleBatch


# ------------------------------------------------------------------ MLP core
def init_mlp(key, sizes: Sequence[int]) -> list:
    params = []
    for fan_in, fan_out in zip(sizes[:-1], sizes[1:]):
        key, sub = jax.random.split(key)
        w = jax.random.normal(sub, (fan_in, fan_out)) * jnp.sqrt(
            2.0 / fan_in)
        params.append({"w": w.astype(jnp.float32),
                       "b": jnp.zeros(fan_out, jnp.float32)})
    return params


def mlp_apply(params: list, x: jnp.ndarray) -> jnp.ndarray:
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


class Policy:
    def compute_actions(self, obs: np.ndarray) -> Tuple[np.ndarray, dict]:
        raise NotImplementedError

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        raise NotImplementedError

    def get_weights(self):
        raise NotImplementedError

    def set_weights(self, weights) -> None:
        raise NotImplementedError

    def postprocess_trajectory(self, batch: SampleBatch) -> SampleBatch:
        return batch


# ---------------------------------------------------------------------- PPO
class PPOPolicy(Policy):
    def __init__(self, observation_dim: int, num_actions: int,
                 config: Optional[dict] = None):
        cfg = dict(lr=3e-4, gamma=0.99, lam=0.95, clip_param=0.2,
                   entropy_coeff=0.01, vf_coeff=0.5, num_sgd_iter=6,
                   sgd_minibatch_size=128, hidden=(64, 64), seed=0)
        cfg.update(config or {})
        self.cfg = cfg
        key = jax.random.PRNGKey(cfg["seed"])
        kp, kv = jax.random.split(key)
        hidden = tuple(cfg["hidden"])
        self.params = {
            "pi": init_mlp(kp, (observation_dim, *hidden, num_actions)),
            "vf": init_mlp(kv, (observation_dim, *hidden, 1)),
        }
        self.opt = optax.adam(cfg["lr"])
        self.opt_state = self.opt.init(self.params)
        self._rng = np.random.default_rng(cfg["seed"])

        @jax.jit
        def _forward(params, obs):
            logits = mlp_apply(params["pi"], obs)
            values = mlp_apply(params["vf"], obs)[..., 0]
            return logits, values

        @jax.jit
        def _update(params, opt_state, obs, actions, old_logp, advantages,
                    returns):
            def loss_fn(p):
                logits = mlp_apply(p["pi"], obs)
                values = mlp_apply(p["vf"], obs)[..., 0]
                logp_all = jax.nn.log_softmax(logits)
                logp = jnp.take_along_axis(
                    logp_all, actions[:, None], axis=1)[:, 0]
                ratio = jnp.exp(logp - old_logp)
                clipped = jnp.clip(ratio, 1 - cfg["clip_param"],
                                   1 + cfg["clip_param"])
                pg_loss = -jnp.mean(
                    jnp.minimum(ratio * advantages, clipped * advantages))
                vf_loss = jnp.mean((values - returns) ** 2)
                entropy = -jnp.mean(
                    jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
                total = (pg_loss + cfg["vf_coeff"] * vf_loss
                         - cfg["entropy_coeff"] * entropy)
                return total, (pg_loss, vf_loss, entropy)

            grads, aux = jax.grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, aux

        self._forward = _forward
        self._update = _update

    # ------------------------------------------------------------ acting
    def compute_actions(self, obs: np.ndarray) -> Tuple[np.ndarray, dict]:
        obs = np.atleast_2d(np.asarray(obs, np.float32))
        logits, values = self._forward(self.params, obs)
        logits = np.asarray(logits)
        actions = sample_categorical(logits, self._rng)
        logp_all = logits - _logsumexp(logits)
        logp = logp_all[np.arange(len(actions)), actions]
        return actions, {sb.VALUES: np.asarray(values),
                         sb.LOGP: logp}

    # ------------------------------------------------- GAE postprocessing
    def postprocess_trajectory(self, batch: SampleBatch) -> SampleBatch:
        rewards = np.asarray(batch[sb.REWARDS], np.float32)
        values = np.asarray(batch[sb.VALUES], np.float32)
        dones = np.asarray(batch[sb.DONES], bool)
        gamma, lam = self.cfg["gamma"], self.cfg["lam"]
        n = len(rewards)
        adv = np.zeros(n, np.float32)
        last = 0.0
        for t in range(n - 1, -1, -1):
            next_v = 0.0 if (t == n - 1 or dones[t]) else values[t + 1]
            nonterminal = 0.0 if dones[t] else 1.0
            delta = rewards[t] + gamma * next_v - values[t]
            last = delta + gamma * lam * nonterminal * last
            adv[t] = last
        batch[sb.ADVANTAGES] = adv
        batch[sb.RETURNS] = adv + values
        return batch

    # ------------------------------------------------------------ learning
    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        adv = np.asarray(batch[sb.ADVANTAGES], np.float32)
        batch[sb.ADVANTAGES] = (adv - adv.mean()) / (adv.std() + 1e-8)
        stats = (0.0, 0.0, 0.0)
        for _ in range(self.cfg["num_sgd_iter"]):
            shuffled = batch.shuffle(self._rng)
            for mb in shuffled.minibatches(self.cfg["sgd_minibatch_size"]):
                self.params, self.opt_state, aux = self._update(
                    self.params, self.opt_state,
                    jnp.asarray(np.asarray(mb[sb.OBS], np.float32)),
                    jnp.asarray(np.asarray(mb[sb.ACTIONS], np.int32)),
                    jnp.asarray(np.asarray(mb[sb.LOGP], np.float32)),
                    jnp.asarray(np.asarray(mb[sb.ADVANTAGES], np.float32)),
                    jnp.asarray(np.asarray(mb[sb.RETURNS], np.float32)))
                stats = tuple(float(a) for a in aux)
        return {"policy_loss": stats[0], "vf_loss": stats[1],
                "entropy": stats[2]}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.device_put(weights)


# ---------------------------------------------------------------------- DQN
class DQNPolicy(Policy):
    def __init__(self, observation_dim: int, num_actions: int,
                 config: Optional[dict] = None):
        cfg = dict(lr=1e-3, gamma=0.99, epsilon=1.0, epsilon_min=0.05,
                   epsilon_decay=0.995, target_update_freq=200,
                   hidden=(64, 64), seed=0, double_q=True)
        cfg.update(config or {})
        self.cfg = cfg
        self.num_actions = num_actions
        key = jax.random.PRNGKey(cfg["seed"])
        hidden = tuple(cfg["hidden"])
        self.params = init_mlp(key, (observation_dim, *hidden, num_actions))
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.params)
        self.opt = optax.adam(cfg["lr"])
        self.opt_state = self.opt.init(self.params)
        self.epsilon = cfg["epsilon"]
        self._steps = 0
        self._rng = np.random.default_rng(cfg["seed"])

        @jax.jit
        def _q(params, obs):
            return mlp_apply(params, obs)

        @jax.jit
        def _update(params, target_params, opt_state, obs, actions,
                    rewards, next_obs, dones):
            def loss_fn(p):
                q = mlp_apply(p, obs)
                q_taken = jnp.take_along_axis(
                    q, actions[:, None], axis=1)[:, 0]
                q_next_target = mlp_apply(target_params, next_obs)
                if cfg["double_q"]:
                    best = jnp.argmax(mlp_apply(p, next_obs), axis=1)
                    q_next = jnp.take_along_axis(
                        q_next_target, best[:, None], axis=1)[:, 0]
                else:
                    q_next = jnp.max(q_next_target, axis=1)
                target = rewards + cfg["gamma"] * (1.0 - dones) * \
                    jax.lax.stop_gradient(q_next)
                return jnp.mean((q_taken - target) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._q = _q
        self._update = _update

    def compute_actions(self, obs: np.ndarray) -> Tuple[np.ndarray, dict]:
        obs = np.atleast_2d(np.asarray(obs, np.float32))
        q = np.asarray(self._q(self.params, obs))
        actions = np.argmax(q, axis=1)
        explore = self._rng.random(len(actions)) < self.epsilon
        random_actions = self._rng.integers(self.num_actions,
                                            size=len(actions))
        actions = np.where(explore, random_actions, actions)
        return actions, {}

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        self.params, self.opt_state, loss = self._update(
            self.params, self.target_params, self.opt_state,
            jnp.asarray(np.asarray(batch[sb.OBS], np.float32)),
            jnp.asarray(np.asarray(batch[sb.ACTIONS], np.int32)),
            jnp.asarray(np.asarray(batch[sb.REWARDS], np.float32)),
            jnp.asarray(np.asarray(batch[sb.NEXT_OBS], np.float32)),
            jnp.asarray(np.asarray(batch[sb.DONES], np.float32)))
        self._steps += 1
        if self._steps % self.cfg["target_update_freq"] == 0:
            self.target_params = jax.tree_util.tree_map(
                lambda x: x, self.params)
        self.epsilon = max(self.cfg["epsilon_min"],
                           self.epsilon * self.cfg["epsilon_decay"])
        return {"td_loss": float(loss), "epsilon": self.epsilon}

    def get_weights(self):
        return jax.device_get({"params": self.params,
                               "target": self.target_params,
                               "epsilon": self.epsilon})

    def set_weights(self, weights) -> None:
        self.params = jax.device_put(weights["params"])
        self.target_params = jax.device_put(weights["target"])
        self.epsilon = weights["epsilon"]


def _logsumexp(x: np.ndarray) -> np.ndarray:
    m = x.max(axis=1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(axis=1, keepdims=True))


def sample_categorical(logits: np.ndarray,
                       rng: np.random.Generator) -> np.ndarray:
    """Gumbel-max sampling on host — keeps the jitted forward stateless.
    Shared by every discrete policy."""
    u = rng.uniform(1e-9, 1.0, size=logits.shape)
    return np.argmax(logits - np.log(-np.log(u)), axis=1)
