"""SampleBatch — columnar rollout data (reference:
rllib/policy/sample_batch.py). A dict of parallel numpy arrays; concat
and minibatch slicing are the two operations the training loop needs.
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
NEXT_OBS = "next_obs"
LOGITS = "logits"
LOGP = "logp"
VALUES = "values"
ADVANTAGES = "advantages"
RETURNS = "returns"


class SampleBatch(dict):
    @property
    def count(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        batches = [b for b in batches if b.count]
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch({
            k: np.concatenate([np.asarray(b[k]) for b in batches])
            for k in keys})

    def shuffle(self, rng: np.random.Generator) -> "SampleBatch":
        perm = rng.permutation(self.count)
        return SampleBatch({k: np.asarray(v)[perm]
                            for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        n = self.count
        for start in range(0, n, size):
            yield SampleBatch({k: np.asarray(v)[start:start + size]
                               for k, v in self.items()})
