"""A3C: asynchronous advantage actor-critic.

Reference behavior: rllib/agents/a3c/a3c.py — the ASYNC execution plan
(AsyncGradients): each rollout worker computes GRADIENTS from its own
fragment with whatever weights it has; the learner applies them the
moment any worker finishes (ray.wait on the in-flight set) and ships
fresh weights back to THAT worker only. Gradients are stale by up to
one round trip — the A3C trade, distinct from A2C's synchronous
sample-then-learn batch. Built on the compute_gradients/apply_gradients
seam of A2CPolicy.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.policy_extra import A2CPolicy
from ray_tpu.rllib.rollout_worker import RolloutWorker


class _GradientWorker(RolloutWorker):
    def sample_gradients(self, num_steps: int):
        """One fragment -> (grads, timesteps, stats), computed with this
        worker's CURRENT weights (possibly stale — that is A3C)."""
        batch = self.sample(num_steps)
        grads, stats = self.policy.compute_gradients(batch)
        return grads, batch.count, stats


class A3CTrainer:
    """Async-gradients trainer (Tune Trainable protocol like the other
    trainers)."""

    _default_config: Dict[str, Any] = {
        "env": None,
        "env_config": {},
        "num_workers": 2,
        "rollout_fragment_length": 64,
        "grads_per_iter": 16,   # applied gradients per train() call
        "policy_config": {},
        "seed": 0,
    }

    def __init__(self, config: Optional[dict] = None, env: Any = None):
        self.config = dict(self._default_config)
        self.config.update(config or {})
        if env is not None:
            self.config["env"] = env
        if self.config["env"] is None:
            raise ValueError("config['env'] is required")
        if self.config["num_workers"] < 1:
            raise ValueError(
                "A3C's execution plan is inherently asynchronous over "
                "remote workers; num_workers must be >= 1 (use "
                "A2CTrainer for the synchronous local plan)")
        self._local_worker = _GradientWorker(
            self.config["env"], A2CPolicy,
            self.config.get("policy_config", {}),
            self.config.get("env_config", {}), worker_index=0)
        self.local_policy = self._local_worker.policy
        remote_cls = ray_tpu.remote(num_cpus=0.5)(_GradientWorker)
        self.workers = [
            remote_cls.remote(self.config["env"], A2CPolicy,
                              self.config.get("policy_config", {}),
                              self.config.get("env_config", {}),
                              worker_index=i + 1)
            for i in range(self.config["num_workers"])]
        weights = ray_tpu.put(self.local_policy.get_weights())
        ray_tpu.get([w.set_weights.remote(weights) for w in self.workers])
        self._iteration = 0
        self._timesteps_total = 0
        self._grads_applied = 0

    # ------------------------------------------------------------- training
    def training_step(self) -> Dict[str, float]:
        frag = self.config["rollout_fragment_length"]
        in_flight = {w.sample_gradients.remote(frag): w
                     for w in self.workers}
        stats: Dict[str, float] = {}
        applied = 0
        while applied < self.config["grads_per_iter"]:
            # wait-any: apply whichever worker's gradients land first
            ready, _ = ray_tpu.wait(list(in_flight), num_returns=1,
                                    timeout=60)
            if not ready:
                break
            ref = ready[0]
            worker = in_flight.pop(ref)
            grads, count, stats = ray_tpu.get([ref])[0]
            self.local_policy.apply_gradients(grads)
            self._timesteps_total += count
            applied += 1
            self._grads_applied += 1
            # fresh weights go back to THAT worker only; the others keep
            # sampling with their (slightly stale) copies
            worker.set_weights.remote(self.local_policy.get_weights())
            in_flight[worker.sample_gradients.remote(frag)] = worker
        # Drain stragglers in ONE bounded wait and USE their work —
        # computed gradients are not free; discarding them wastes a
        # fragment per worker per iteration.
        try:
            results = ray_tpu.get(list(in_flight), timeout=120)
        except Exception as e:  # noqa: BLE001 — a wedged worker
            import logging

            logging.getLogger(__name__).warning(
                "a3c straggler drain failed: %r", e)
            results = []
        for grads, count, worker_stats in results:
            self.local_policy.apply_gradients(grads)
            self._timesteps_total += count
            self._grads_applied += 1
            stats = worker_stats
        return stats

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        stats = self.training_step()
        self._iteration += 1
        metrics = ray_tpu.get([w.get_metrics.remote()
                               for w in self.workers])
        rewards = [m["episode_reward_mean"] for m in metrics
                   if not np.isnan(m["episode_reward_mean"])]
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._timesteps_total,
            "grads_applied_total": self._grads_applied,
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else float("nan"),
            "episodes_total": sum(m["episodes_total"] for m in metrics),
            "time_this_iter_s": time.perf_counter() - t0,
            "info": {"learner": stats},
        }

    # --------------------------------------------------------- Trainable
    def get_policy(self) -> A2CPolicy:
        return self.local_policy

    def compute_single_action(self, obs) -> int:
        actions, _ = self.local_policy.compute_actions(obs)
        return int(actions[0])

    def save_checkpoint(self) -> dict:
        return {"weights": self.local_policy.get_weights(),
                "iteration": self._iteration}

    def restore(self, checkpoint: dict) -> None:
        self.local_policy.set_weights(checkpoint["weights"])
        self._iteration = checkpoint["iteration"]
        weights = ray_tpu.put(self.local_policy.get_weights())
        ray_tpu.get([w.set_weights.remote(weights) for w in self.workers])

    def stop(self) -> None:
        for w in self.workers:
            ray_tpu.kill(w)
        self.workers = []
