"""ray_tpu.rllib — reinforcement learning on the actor fleet.

Reference surface: rllib/ (agents, rollout workers, sample batches,
replay). Policies are JAX (jit'd stateless functions over param pytrees);
sampling is an actor fleet; learning runs on the local worker.
"""

from ray_tpu.rllib.agents import (  # noqa: F401
    A2CTrainer,
    BCTrainer,
    CQLTrainer,
    DDPGTrainer,
    DQNTrainer,
    APPOTrainer,
    IMPALATrainer,
    LinTSTrainer,
    LinUCBTrainer,
    MARWILTrainer,
    PGTrainer,
    PPOTrainer,
    SACContinuousTrainer,
    SACTrainer,
    TD3Trainer,
    Trainer,
)
from ray_tpu.rllib.env import (  # noqa: F401
    CartPoleEnv,
    Env,
    LinearBanditEnv,
    PendulumEnv,
    StatelessGuessEnv,
    make_env,
)
from ray_tpu.rllib.a3c import A3CTrainer  # noqa: F401
from ray_tpu.rllib.es import ARSTrainer, ESTrainer  # noqa: F401
from ray_tpu.rllib.multi_agent import (  # noqa: F401
    MultiAgentEnv,
    MultiAgentRolloutWorker,
    MultiAgentTrainer,
    TwoStepGuessEnv,
)
from ray_tpu.rllib.qmix import (  # noqa: F401
    QMixTrainer,
    TwoStepCoopEnv,
    VDNTrainer,
)
from ray_tpu.rllib.offline import (  # noqa: F401
    JsonReader,
    JsonWriter,
    collect_episodes,
)
from ray_tpu.rllib.policy import DQNPolicy, PPOPolicy, Policy  # noqa: F401
from ray_tpu.rllib.policy_bandit import (  # noqa: F401
    LinTSPolicy,
    LinUCBPolicy,
)
from ray_tpu.rllib.policy_continuous import (  # noqa: F401
    ContinuousSACPolicy,
    CQLPolicy,
    DDPGPolicy,
    TD3Policy,
)
from ray_tpu.rllib.policy_extra import (  # noqa: F401
    A2CPolicy,
    IMPALAPolicy,
    SACPolicy,
)
from ray_tpu.rllib.policy_pg import MARWILPolicy, PGPolicy  # noqa: F401
from ray_tpu.rllib.rollout_worker import (  # noqa: F401
    ReplayBuffer,
    RolloutWorker,
    WorkerSet,
)
from ray_tpu.rllib.sample_batch import SampleBatch  # noqa: F401

__all__ = [
    "Trainer", "PPOTrainer", "DQNTrainer", "A2CTrainer", "SACTrainer",
    "IMPALATrainer", "APPOTrainer", "PGTrainer", "MARWILTrainer", "BCTrainer",
    "DDPGTrainer", "TD3Trainer", "SACContinuousTrainer", "CQLTrainer",
    "LinUCBTrainer", "LinTSTrainer",
    "ESTrainer", "ARSTrainer", "A3CTrainer",
    "Policy", "PPOPolicy", "DQNPolicy", "A2CPolicy",
    "SACPolicy", "IMPALAPolicy", "PGPolicy", "MARWILPolicy",
    "DDPGPolicy", "TD3Policy", "ContinuousSACPolicy", "CQLPolicy",
    "LinUCBPolicy", "LinTSPolicy",
    "RolloutWorker", "WorkerSet",
    "ReplayBuffer", "SampleBatch", "Env", "CartPoleEnv",
    "StatelessGuessEnv", "PendulumEnv", "LinearBanditEnv", "make_env",
    "JsonReader", "JsonWriter", "collect_episodes",
    "MultiAgentEnv", "MultiAgentTrainer", "MultiAgentRolloutWorker",
    "TwoStepGuessEnv", "QMixTrainer", "VDNTrainer", "TwoStepCoopEnv",
]
