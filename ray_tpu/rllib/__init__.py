"""ray_tpu.rllib — reinforcement learning on the actor fleet.

Reference surface: rllib/ (agents, rollout workers, sample batches,
replay). Policies are JAX (jit'd stateless functions over param pytrees);
sampling is an actor fleet; learning runs on the local worker.
"""

from ray_tpu.rllib.agents import (  # noqa: F401
    A2CTrainer,
    DQNTrainer,
    IMPALATrainer,
    PPOTrainer,
    SACTrainer,
    Trainer,
)
from ray_tpu.rllib.env import (  # noqa: F401
    CartPoleEnv,
    Env,
    StatelessGuessEnv,
    make_env,
)
from ray_tpu.rllib.policy import DQNPolicy, PPOPolicy, Policy  # noqa: F401
from ray_tpu.rllib.policy_extra import (  # noqa: F401
    A2CPolicy,
    IMPALAPolicy,
    SACPolicy,
)
from ray_tpu.rllib.rollout_worker import (  # noqa: F401
    ReplayBuffer,
    RolloutWorker,
    WorkerSet,
)
from ray_tpu.rllib.sample_batch import SampleBatch  # noqa: F401

__all__ = [
    "Trainer", "PPOTrainer", "DQNTrainer", "A2CTrainer", "SACTrainer",
    "IMPALATrainer", "Policy", "PPOPolicy", "DQNPolicy", "A2CPolicy",
    "SACPolicy", "IMPALAPolicy", "RolloutWorker", "WorkerSet",
    "ReplayBuffer", "SampleBatch", "Env", "CartPoleEnv",
    "StatelessGuessEnv", "make_env",
]
