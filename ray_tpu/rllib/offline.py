"""Offline data IO: JSON sample writers/readers.

Reference: rllib/offline/json_writer.py + json_reader.py — SampleBatches
serialized as JSON lines so experiences collected by one run train
another (behavior cloning, MARWIL). Columns are stored as nested lists;
dtypes restore on read.
"""

from __future__ import annotations

import json
import os
from typing import Iterator, List, Optional, Union

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


class JsonWriter:
    """Append SampleBatches to a .json lines file."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._fp = open(path, "a")

    def write(self, batch: SampleBatch) -> None:
        row = {k: np.asarray(v).tolist() for k, v in batch.items()}
        self._fp.write(json.dumps(row) + "\n")
        self._fp.flush()

    def close(self) -> None:
        self._fp.close()


class JsonReader:
    """Read SampleBatches back; `next()` cycles forever (reference:
    json_reader.py next() loops over the input files)."""

    def __init__(self, path_or_batches: Union[str, List[SampleBatch]]):
        if isinstance(path_or_batches, str):
            self.batches = list(_read_file(path_or_batches))
        else:
            self.batches = list(path_or_batches)
        if not self.batches:
            raise ValueError("offline input is empty")
        self._i = 0

    def next(self) -> SampleBatch:
        batch = self.batches[self._i % len(self.batches)]
        self._i += 1
        return batch

    def __iter__(self) -> Iterator[SampleBatch]:
        return iter(self.batches)


def _read_file(path: str) -> Iterator[SampleBatch]:
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            yield SampleBatch({k: np.asarray(v) for k, v in row.items()})


def collect_episodes(env, policy, num_steps: int,
                     writer: Optional[JsonWriter] = None,
                     seed: int = 0) -> SampleBatch:
    """Roll a policy in an env for num_steps and return (and optionally
    persist) the experience — the seam tests and examples use to build
    offline datasets."""
    from ray_tpu.rllib import sample_batch as sb

    env.seed(seed)
    obs = env.reset()
    continuous = bool(getattr(env, "action_dim", 0))
    cols = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.DONES,
                            sb.NEXT_OBS)}
    for _ in range(num_steps):
        actions, _ = policy.compute_actions(obs)
        if continuous:  # int() would silently truncate torques
            action = np.asarray(actions, np.float32).reshape(-1)
        else:
            action = int(np.asarray(actions).reshape(-1)[0])
        next_obs, reward, done, _ = env.step(action)
        cols[sb.OBS].append(obs)
        cols[sb.ACTIONS].append(action)
        cols[sb.REWARDS].append(reward)
        cols[sb.DONES].append(done)
        cols[sb.NEXT_OBS].append(next_obs)
        obs = env.reset() if done else next_obs
    batch = SampleBatch({k: np.asarray(v) for k, v in cols.items()})
    if writer is not None:
        writer.write(batch)
    return batch
