"""Additional JAX policies: A2C, discrete SAC, and IMPALA (V-trace).

Reference behavior: rllib/agents/a3c/ (synchronous variant = A2C),
rllib/agents/sac/ (maximum-entropy, discrete-action head), and
rllib/agents/impala/vtrace.py (importance-corrected off-policy values).
Same TPU idiom as policy.py: pure-functional param pytrees, jitted
update steps with static shapes, optax.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.policy import (
    Policy,
    _logsumexp,
    init_mlp,
    mlp_apply,
    sample_categorical,
)
from ray_tpu.rllib.sample_batch import SampleBatch


# ---------------------------------------------------------------------- A2C
class A2CPolicy(Policy):
    """Synchronous advantage actor-critic: one SGD pass per rollout
    batch over n-step returns (reference: a3c_torch_policy.py, run
    synchronously as A2C)."""

    def __init__(self, observation_dim: int, num_actions: int,
                 config: Optional[dict] = None):
        cfg = dict(lr=1e-3, gamma=0.99, entropy_coeff=0.01, vf_coeff=0.5,
                   hidden=(64, 64), seed=0)
        cfg.update(config or {})
        self.cfg = cfg
        key = jax.random.PRNGKey(cfg["seed"])
        kp, kv = jax.random.split(key)
        hidden = tuple(cfg["hidden"])
        self.params = {
            "pi": init_mlp(kp, (observation_dim, *hidden, num_actions)),
            "vf": init_mlp(kv, (observation_dim, *hidden, 1)),
        }
        self.opt = optax.adam(cfg["lr"])
        self.opt_state = self.opt.init(self.params)
        self._rng = np.random.default_rng(cfg["seed"])

        @jax.jit
        def _forward(params, obs):
            return (mlp_apply(params["pi"], obs),
                    mlp_apply(params["vf"], obs)[..., 0])

        def _grads_impl(params, obs, actions, returns):
            """Gradients WITHOUT applying them — the ONE loss
            definition; the synchronous update and the A3C seam
            (workers compute, learner applies) both compose from it."""
            def loss_fn(p):
                logits = mlp_apply(p["pi"], obs)
                values = mlp_apply(p["vf"], obs)[..., 0]
                logp_all = jax.nn.log_softmax(logits)
                logp = jnp.take_along_axis(
                    logp_all, actions[:, None], axis=1)[:, 0]
                adv = jax.lax.stop_gradient(returns - values)
                pg_loss = -jnp.mean(logp * adv)
                vf_loss = jnp.mean((values - returns) ** 2)
                entropy = -jnp.mean(
                    jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
                total = (pg_loss + cfg["vf_coeff"] * vf_loss
                         - cfg["entropy_coeff"] * entropy)
                return total, (pg_loss, vf_loss, entropy)

            return jax.grad(loss_fn, has_aux=True)(params)

        @jax.jit
        def _update(params, opt_state, obs, actions, returns):
            grads, aux = _grads_impl(params, obs, actions, returns)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, aux

        @jax.jit
        def _apply(params, opt_state, grads):
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._forward = _forward
        self._update = _update
        self._grads = jax.jit(_grads_impl)
        self._apply = _apply

    def compute_gradients(self, batch):
        """(grads, stats) from one postprocessed batch — the A3C seam."""
        grads, aux = self._grads(
            self.params,
            jnp.asarray(np.asarray(batch[sb.OBS], np.float32)),
            jnp.asarray(np.asarray(batch[sb.ACTIONS], np.int32)),
            jnp.asarray(np.asarray(batch[sb.RETURNS], np.float32)))
        return jax.device_get(grads), {
            "policy_loss": float(aux[0]), "vf_loss": float(aux[1]),
            "entropy": float(aux[2])}

    def apply_gradients(self, grads) -> None:
        self.params, self.opt_state = self._apply(
            self.params, self.opt_state, jax.device_put(grads))

    def compute_actions(self, obs: np.ndarray) -> Tuple[np.ndarray, dict]:
        obs = np.atleast_2d(np.asarray(obs, np.float32))
        logits, values = self._forward(self.params, obs)
        actions = sample_categorical(np.asarray(logits), self._rng)
        return actions, {sb.VALUES: np.asarray(values)}

    def postprocess_trajectory(self, batch: SampleBatch) -> SampleBatch:
        rewards = np.asarray(batch[sb.REWARDS], np.float32)
        dones = np.asarray(batch[sb.DONES], bool)
        gamma = self.cfg["gamma"]
        n = len(rewards)
        returns = np.zeros(n, np.float32)
        # truncated (non-terminal) fragment: bootstrap from the value of
        # the state AFTER the last step, not the last observed state
        running = 0.0
        if not dones[-1]:
            last_next = np.atleast_2d(np.asarray(
                batch[sb.NEXT_OBS][-1], np.float32))
            _, v = self._forward(self.params, last_next)
            running = float(np.asarray(v)[0])
        for t in range(n - 1, -1, -1):
            if dones[t]:
                running = rewards[t]
            else:
                running = rewards[t] + gamma * running
            returns[t] = running
        batch[sb.RETURNS] = returns
        return batch

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state,
            jnp.asarray(np.asarray(batch[sb.OBS], np.float32)),
            jnp.asarray(np.asarray(batch[sb.ACTIONS], np.int32)),
            jnp.asarray(np.asarray(batch[sb.RETURNS], np.float32)))
        pg, vf, ent = (float(a) for a in aux)
        return {"policy_loss": pg, "vf_loss": vf, "entropy": ent}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.device_put(weights)


# ---------------------------------------------------------------------- SAC
class SACPolicy(Policy):
    """Discrete-action soft actor-critic: twin soft-Q networks, a
    stochastic policy trained against the soft value, and temperature
    alpha tuned toward a target entropy (reference: agents/sac/ with the
    discrete-head variant)."""

    def __init__(self, observation_dim: int, num_actions: int,
                 config: Optional[dict] = None):
        cfg = dict(lr=3e-4, gamma=0.99, tau=0.01, seed=0, hidden=(64, 64),
                   initial_alpha=0.2, target_entropy=None)
        cfg.update(config or {})
        if cfg["target_entropy"] is None:
            cfg["target_entropy"] = 0.4 * float(np.log(num_actions))
        self.cfg = cfg
        self.num_actions = num_actions
        key = jax.random.PRNGKey(cfg["seed"])
        kp, k1, k2 = jax.random.split(key, 3)
        hidden = tuple(cfg["hidden"])
        self.params = {
            "pi": init_mlp(kp, (observation_dim, *hidden, num_actions)),
            "q1": init_mlp(k1, (observation_dim, *hidden, num_actions)),
            "q2": init_mlp(k2, (observation_dim, *hidden, num_actions)),
            "log_alpha": jnp.asarray(
                np.log(cfg["initial_alpha"]), jnp.float32),
        }
        self.target = {"q1": self.params["q1"], "q2": self.params["q2"]}
        self.opt = optax.adam(cfg["lr"])
        self.opt_state = self.opt.init(self.params)
        self._rng = np.random.default_rng(cfg["seed"])

        @jax.jit
        def _logits(params, obs):
            return mlp_apply(params["pi"], obs)

        @jax.jit
        def _update(params, target, opt_state, obs, actions, rewards,
                    next_obs, dones):
            def loss_fn(p):
                alpha = jnp.exp(p["log_alpha"])
                # soft state value of next state under the current policy
                next_logits = mlp_apply(p["pi"], next_obs)
                next_logp = jax.nn.log_softmax(next_logits)
                next_probs = jnp.exp(next_logp)
                q1_t = mlp_apply(target["q1"], next_obs)
                q2_t = mlp_apply(target["q2"], next_obs)
                q_t = jnp.minimum(q1_t, q2_t)
                v_next = jnp.sum(
                    next_probs * (q_t - alpha * next_logp), axis=1)
                target_q = rewards + cfg["gamma"] * (1.0 - dones) * \
                    jax.lax.stop_gradient(v_next)
                q1 = jnp.take_along_axis(
                    mlp_apply(p["q1"], obs), actions[:, None], axis=1)[:, 0]
                q2 = jnp.take_along_axis(
                    mlp_apply(p["q2"], obs), actions[:, None], axis=1)[:, 0]
                q_loss = jnp.mean((q1 - target_q) ** 2
                                  + (q2 - target_q) ** 2)
                # policy: maximize soft value under current Qs
                logits = mlp_apply(p["pi"], obs)
                logp = jax.nn.log_softmax(logits)
                probs = jnp.exp(logp)
                q_min = jax.lax.stop_gradient(jnp.minimum(
                    mlp_apply(p["q1"], obs), mlp_apply(p["q2"], obs)))
                # detached alpha: the actor objective must not inject a
                # -alpha*H gradient into the temperature (that is the
                # alpha_loss controller's job alone)
                alpha_sg = jax.lax.stop_gradient(alpha)
                pi_loss = jnp.mean(jnp.sum(
                    probs * (alpha_sg * logp - q_min), axis=1))
                # temperature: match target entropy
                entropy = -jnp.sum(probs * logp, axis=1)
                alpha_loss = jnp.mean(
                    p["log_alpha"]
                    * jax.lax.stop_gradient(
                        entropy - cfg["target_entropy"]))
                return q_loss + pi_loss + alpha_loss, (
                    q_loss, pi_loss, jnp.mean(entropy), alpha)

            grads, aux = jax.grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            new_target = jax.tree_util.tree_map(
                lambda t, o: (1 - cfg["tau"]) * t + cfg["tau"] * o,
                target, {"q1": params["q1"], "q2": params["q2"]})
            return params, new_target, opt_state, aux

        self._logits_fn = _logits
        self._update = _update

    def compute_actions(self, obs: np.ndarray) -> Tuple[np.ndarray, dict]:
        obs = np.atleast_2d(np.asarray(obs, np.float32))
        logits = np.asarray(self._logits_fn(self.params, obs))
        return sample_categorical(logits, self._rng), {}

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        self.params, self.target, self.opt_state, aux = self._update(
            self.params, self.target, self.opt_state,
            jnp.asarray(np.asarray(batch[sb.OBS], np.float32)),
            jnp.asarray(np.asarray(batch[sb.ACTIONS], np.int32)),
            jnp.asarray(np.asarray(batch[sb.REWARDS], np.float32)),
            jnp.asarray(np.asarray(batch[sb.NEXT_OBS], np.float32)),
            jnp.asarray(np.asarray(batch[sb.DONES], np.float32)))
        q_loss, pi_loss, entropy, alpha = (float(a) for a in aux)
        return {"q_loss": q_loss, "policy_loss": pi_loss,
                "entropy": entropy, "alpha": alpha}

    def get_weights(self):
        return jax.device_get({"params": self.params,
                               "target": self.target})

    def set_weights(self, weights) -> None:
        self.params = jax.device_put(weights["params"])
        self.target = jax.device_put(weights["target"])


# ------------------------------------------------------------------- IMPALA
def vtrace(behavior_logp, target_logp, rewards, values, bootstrap,
           dones, gamma, clip_rho=1.0, clip_c=1.0):
    """V-trace targets (reference: rllib/agents/impala/vtrace.py, the
    IMPALA paper's off-policy correction), vectorized with lax.scan over
    time."""
    rhos = jnp.minimum(jnp.exp(target_logp - behavior_logp), clip_rho)
    cs = jnp.minimum(jnp.exp(target_logp - behavior_logp), clip_c)
    discounts = gamma * (1.0 - dones)
    next_values = jnp.concatenate([values[1:], bootstrap[None]])
    deltas = rhos * (rewards + discounts * next_values - values)

    def step(acc, xs):
        delta_t, discount_t, c_t = xs
        acc = delta_t + discount_t * c_t * acc
        return acc, acc

    _, corrections = jax.lax.scan(
        step, jnp.zeros_like(bootstrap),
        (deltas[::-1], discounts[::-1], cs[::-1]))
    vs = values + corrections[::-1]
    next_vs = jnp.concatenate([vs[1:], bootstrap[None]])
    pg_advantages = rhos * (rewards + discounts * next_vs - values)
    return vs, pg_advantages


class IMPALAPolicy(Policy):
    """Importance-weighted actor-learner: workers sample with a stale
    policy; the learner corrects via V-trace (reference:
    agents/impala/)."""

    def __init__(self, observation_dim: int, num_actions: int,
                 config: Optional[dict] = None):
        cfg = dict(lr=6e-4, gamma=0.99, entropy_coeff=0.01, vf_coeff=0.5,
                   clip_rho=1.0, clip_c=1.0, hidden=(64, 64), seed=0)
        cfg.update(config or {})
        self.cfg = cfg
        key = jax.random.PRNGKey(cfg["seed"])
        kp, kv = jax.random.split(key)
        hidden = tuple(cfg["hidden"])
        self.params = {
            "pi": init_mlp(kp, (observation_dim, *hidden, num_actions)),
            "vf": init_mlp(kv, (observation_dim, *hidden, 1)),
        }
        self.opt = optax.adam(cfg["lr"])
        self.opt_state = self.opt.init(self.params)
        self._rng = np.random.default_rng(cfg["seed"])

        @jax.jit
        def _forward(params, obs):
            return (mlp_apply(params["pi"], obs),
                    mlp_apply(params["vf"], obs)[..., 0])

        pg_loss_fn = self._pg_loss

        @jax.jit
        def _update(params, opt_state, obs, actions, behavior_logp,
                    rewards, dones, last_next_obs):
            def loss_fn(p):
                logits = mlp_apply(p["pi"], obs)
                values = mlp_apply(p["vf"], obs)[..., 0]
                logp_all = jax.nn.log_softmax(logits)
                target_logp = jnp.take_along_axis(
                    logp_all, actions[:, None], axis=1)[:, 0]
                # truncated fragments bootstrap from V(s_{T+1})
                bootstrap = jnp.where(
                    dones[-1] > 0, 0.0,
                    mlp_apply(p["vf"], last_next_obs[None])[-1, 0])
                vs, pg_adv = vtrace(
                    behavior_logp, jax.lax.stop_gradient(target_logp),
                    rewards, jax.lax.stop_gradient(values),
                    jax.lax.stop_gradient(bootstrap), dones,
                    cfg["gamma"], cfg["clip_rho"], cfg["clip_c"])
                pg_loss = pg_loss_fn(
                    target_logp, behavior_logp,
                    jax.lax.stop_gradient(pg_adv))
                vf_loss = jnp.mean(
                    (values - jax.lax.stop_gradient(vs)) ** 2)
                entropy = -jnp.mean(
                    jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
                total = (pg_loss + cfg["vf_coeff"] * vf_loss
                         - cfg["entropy_coeff"] * entropy)
                return total, (pg_loss, vf_loss, entropy)

            grads, aux = jax.grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, aux

        self._forward = _forward
        self._update = _update

    def _pg_loss(self, target_logp, behavior_logp, adv):
        """Policy-gradient term over V-trace advantages. The seam
        APPO overrides with the PPO clipped surrogate (the same
        loss-hook pattern as ContinuousSACPolicy/CQLPolicy)."""
        return -jnp.mean(target_logp * adv)

    def compute_actions(self, obs: np.ndarray) -> Tuple[np.ndarray, dict]:
        obs = np.atleast_2d(np.asarray(obs, np.float32))
        logits, _values = self._forward(self.params, obs)
        logits = np.asarray(logits)
        actions = sample_categorical(logits, self._rng)
        logp_all = logits - _logsumexp(logits)
        logp = logp_all[np.arange(len(actions)), actions]
        return actions, {sb.LOGP: logp}

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state,
            jnp.asarray(np.asarray(batch[sb.OBS], np.float32)),
            jnp.asarray(np.asarray(batch[sb.ACTIONS], np.int32)),
            jnp.asarray(np.asarray(batch[sb.LOGP], np.float32)),
            jnp.asarray(np.asarray(batch[sb.REWARDS], np.float32)),
            jnp.asarray(np.asarray(batch[sb.DONES], np.float32)),
            jnp.asarray(np.asarray(batch[sb.NEXT_OBS][-1], np.float32)))
        pg, vf, ent = (float(a) for a in aux)
        return {"policy_loss": pg, "vf_loss": vf, "entropy": ent}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.device_put(weights)


class APPOPolicy(IMPALAPolicy):
    """Asynchronous PPO (reference: rllib/agents/ppo/appo.py): IMPALA's
    actor-learner architecture and V-trace off-policy correction, with
    PPO's clipped surrogate as the policy loss — the ratio is taken
    against the BEHAVIOR policy that sampled the fragment, so stale
    workers neither explode the update nor need synchronous weight
    locks. Only the pg-loss hook differs from IMPALA."""

    def __init__(self, observation_dim: int, num_actions: int,
                 config: Optional[dict] = None):
        cfg = dict(config or {})
        cfg.setdefault("clip_param", 0.2)
        self._clip_param = cfg["clip_param"]
        super().__init__(observation_dim, num_actions, cfg)

    def _pg_loss(self, target_logp, behavior_logp, adv):
        ratio = jnp.exp(target_logp - behavior_logp)
        clipped = jnp.clip(ratio, 1.0 - self._clip_param,
                           1.0 + self._clip_param)
        return -jnp.mean(jnp.minimum(ratio * adv, clipped * adv))
