"""REINFORCE-family JAX policies: vanilla PG and MARWIL (offline).

Reference behavior: rllib/agents/pg/ (policy-gradient with return-to-go)
and rllib/agents/marwil/ (monotonic advantage re-weighted imitation
learning; BC is MARWIL at beta=0). Re-designed TPU-first like the rest
of the stack: pure-functional param pytrees + jit'd updates.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.policy import (
    Policy,
    init_mlp,
    mlp_apply,
    sample_categorical,
)
from ray_tpu.rllib.sample_batch import SampleBatch


class PGPolicy(Policy):
    """Vanilla policy gradient: -logp * (G - V(s)) with a learned value
    baseline (reference: agents/pg/pg_tf_policy.py post_process_advantages
    uses discounted return-to-go)."""

    def __init__(self, observation_dim: int, num_actions: int,
                 config: Optional[dict] = None):
        cfg = dict(lr=5e-3, gamma=0.99, vf_coeff=0.5, hidden=(64, 64),
                   seed=0)
        cfg.update(config or {})
        self.cfg = cfg
        kp, kv = jax.random.split(jax.random.PRNGKey(cfg["seed"]))
        hidden = tuple(cfg["hidden"])
        self.params = {
            "pi": init_mlp(kp, (observation_dim, *hidden, num_actions)),
            "vf": init_mlp(kv, (observation_dim, *hidden, 1)),
        }
        self.opt = optax.adam(cfg["lr"])
        self.opt_state = self.opt.init(self.params)
        self._rng = np.random.default_rng(cfg["seed"])

        @jax.jit
        def _forward(params, obs):
            return mlp_apply(params["pi"], obs)

        @jax.jit
        def _update(params, opt_state, obs, actions, returns):
            def loss_fn(p):
                logits = mlp_apply(p["pi"], obs)
                values = mlp_apply(p["vf"], obs)[..., 0]
                logp_all = jax.nn.log_softmax(logits)
                logp = jnp.take_along_axis(
                    logp_all, actions[:, None], axis=1)[:, 0]
                adv = returns - jax.lax.stop_gradient(values)
                pg_loss = -jnp.mean(logp * adv)
                vf_loss = jnp.mean((values - returns) ** 2)
                return pg_loss + cfg["vf_coeff"] * vf_loss, (pg_loss,
                                                             vf_loss)

            grads, aux = jax.grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, aux

        self._forward = _forward
        self._update = _update

    def compute_actions(self, obs) -> Tuple[np.ndarray, dict]:
        obs = np.atleast_2d(np.asarray(obs, np.float32))
        logits = np.asarray(self._forward(self.params, obs))
        return sample_categorical(logits, self._rng), {}

    def postprocess_trajectory(self, batch: SampleBatch) -> SampleBatch:
        rewards = np.asarray(batch[sb.REWARDS], np.float32)
        dones = np.asarray(batch[sb.DONES], bool)
        gamma = self.cfg["gamma"]
        returns = np.zeros_like(rewards)
        acc = 0.0
        for t in range(len(rewards) - 1, -1, -1):
            acc = rewards[t] + gamma * (0.0 if dones[t] else acc)
            returns[t] = acc
        batch[sb.RETURNS] = returns
        return batch

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state,
            jnp.asarray(np.asarray(batch[sb.OBS], np.float32)),
            jnp.asarray(np.asarray(batch[sb.ACTIONS], np.int32)),
            jnp.asarray(np.asarray(batch[sb.RETURNS], np.float32)))
        return {"policy_loss": float(aux[0]), "vf_loss": float(aux[1])}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, weights) -> None:
        self.params = jax.device_put(weights)


class MARWILPolicy(PGPolicy):
    """MARWIL: imitation weighted by exp(beta * normalized advantage);
    beta=0 degenerates to behavior cloning (reference:
    agents/marwil/marwil_tf_policy.py, including the moving advantage
    norm c^2 <- c^2 + lr_c (A^2 - c^2))."""

    def __init__(self, observation_dim: int, num_actions: int,
                 config: Optional[dict] = None):
        cfg = dict(beta=1.0, vf_coeff=1.0, ma_lr=1e-3)
        cfg.update(config or {})
        super().__init__(observation_dim, num_actions, cfg)
        self._adv_norm = 1.0  # moving estimate of E[A^2]

        cfg = self.cfg

        @jax.jit
        def _update(params, opt_state, obs, actions, returns, adv_norm):
            def loss_fn(p):
                logits = mlp_apply(p["pi"], obs)
                values = mlp_apply(p["vf"], obs)[..., 0]
                logp_all = jax.nn.log_softmax(logits)
                logp = jnp.take_along_axis(
                    logp_all, actions[:, None], axis=1)[:, 0]
                adv = returns - jax.lax.stop_gradient(values)
                weight = jnp.exp(cfg["beta"] * adv
                                 / (adv_norm + 1e-8))
                bc_loss = -jnp.mean(jax.lax.stop_gradient(weight) * logp)
                vf_loss = jnp.mean((values - returns) ** 2)
                mean_adv_sq = jnp.mean(adv ** 2)
                return (bc_loss + cfg["vf_coeff"] * vf_loss,
                        (bc_loss, vf_loss, mean_adv_sq))

            grads, aux = jax.grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, aux

        self._marwil_update = _update

    def compute_actions(self, obs) -> Tuple[np.ndarray, dict]:
        # evaluation is greedy: imitation policies act by argmax
        obs = np.atleast_2d(np.asarray(obs, np.float32))
        logits = np.asarray(self._forward(self.params, obs))
        return np.argmax(logits, axis=1), {}

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        self.params, self.opt_state, aux = self._marwil_update(
            self.params, self.opt_state,
            jnp.asarray(np.asarray(batch[sb.OBS], np.float32)),
            jnp.asarray(np.asarray(batch[sb.ACTIONS], np.int32)),
            jnp.asarray(np.asarray(batch[sb.RETURNS], np.float32)),
            jnp.asarray(np.sqrt(self._adv_norm), jnp.float32))
        mean_adv_sq = float(aux[2])
        self._adv_norm += self.cfg["ma_lr"] * (mean_adv_sq
                                               - self._adv_norm)
        return {"bc_loss": float(aux[0]), "vf_loss": float(aux[1]),
                "adv_norm": float(self._adv_norm)}
