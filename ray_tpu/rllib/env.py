"""Built-in environments (pure numpy, no gym dependency).

Reference: rllib/env/ (VectorEnv, MultiAgentEnv wrappers). The env API
is gym-classic: reset() -> obs, step(a) -> (obs, reward, done, info).
CartPole uses the standard Barto-Sutton-Anderson dynamics; StatelessGuess
is a one-step env where the optimal policy is learnable in seconds (used
by tests as a fast learning-progress oracle).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class Env:
    observation_dim: int = 0
    num_actions: int = 0

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, dict]:
        raise NotImplementedError

    def seed(self, seed: int) -> None:
        self._rng = np.random.default_rng(seed)


class CartPoleEnv(Env):
    """Classic cart-pole balancing, 200-step episodes."""

    observation_dim = 4
    num_actions = 2

    def __init__(self, max_steps: int = 200, seed: Optional[int] = None):
        self.max_steps = max_steps
        self._rng = np.random.default_rng(seed)
        self._state = None
        self._t = 0

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._t = 0
        return self._state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = 10.0 if action == 1 else -10.0
        gravity, masscart, masspole = 9.8, 1.0, 0.1
        total_mass = masscart + masspole
        length = 0.5
        polemass_length = masspole * length
        tau = 0.02
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + polemass_length * theta_dot ** 2 * sintheta
                ) / total_mass
        thetaacc = (gravity * sintheta - costheta * temp) / (
            length * (4.0 / 3.0 - masspole * costheta ** 2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + tau * x_dot
        x_dot = x_dot + tau * xacc
        theta = theta + tau * theta_dot
        theta_dot = theta_dot + tau * thetaacc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._t += 1
        done = bool(abs(x) > 2.4 or abs(theta) > 0.2095
                    or self._t >= self.max_steps)
        return self._state.astype(np.float32), 1.0, done, {}


class StatelessGuessEnv(Env):
    """One-step env: obs is a random one-hot; reward 1 iff the action
    matches the hot index. Optimal return = 1.0; random = 1/num_actions."""

    def __init__(self, num_actions: int = 4, seed: Optional[int] = None):
        self.num_actions = num_actions
        self.observation_dim = num_actions
        self._rng = np.random.default_rng(seed)
        self._target = 0

    def reset(self) -> np.ndarray:
        self._target = int(self._rng.integers(self.num_actions))
        obs = np.zeros(self.num_actions, dtype=np.float32)
        obs[self._target] = 1.0
        return obs

    def step(self, action: int):
        reward = 1.0 if int(action) == self._target else 0.0
        return self.reset(), reward, True, {}


class PendulumEnv(Env):
    """Classic torque-limited pendulum swing-up — the canonical
    continuous-control env (reference: rllib continuous-action agents
    train on Pendulum-v1). obs = (cos th, sin th, thdot); one torque
    action in [-2, 2]; reward = -(th^2 + 0.1 thdot^2 + 0.001 u^2)."""

    observation_dim = 3
    num_actions = 1  # action_dim alias for policy sizing
    action_dim = 1
    action_low = -2.0
    action_high = 2.0

    def __init__(self, max_steps: int = 200, seed: Optional[int] = None):
        self.max_steps = max_steps
        self._rng = np.random.default_rng(seed)
        self._th = 0.0
        self._thdot = 0.0
        self._t = 0

    def _obs(self) -> np.ndarray:
        return np.array([np.cos(self._th), np.sin(self._th),
                         self._thdot], dtype=np.float32)

    def reset(self) -> np.ndarray:
        self._th = float(self._rng.uniform(-np.pi, np.pi))
        self._thdot = float(self._rng.uniform(-1.0, 1.0))
        self._t = 0
        return self._obs()

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          self.action_low, self.action_high))
        g, m, length, dt = 10.0, 1.0, 1.0, 0.05
        th, thdot = self._th, self._thdot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (3 * g / (2 * length) * np.sin(th)
                         + 3.0 / (m * length ** 2) * u) * dt
        thdot = float(np.clip(thdot, -8.0, 8.0))
        th = th + thdot * dt
        self._th, self._thdot = th, thdot
        self._t += 1
        done = self._t >= self.max_steps
        return self._obs(), -float(cost), done, {}


class LinearBanditEnv(Env):
    """Contextual linear bandit: obs is a random context x; pulling arm a
    pays theta_a . x + noise. One-step episodes (reference:
    rllib/env/bandit_envs discrete linear payoff envs)."""

    def __init__(self, context_dim: int = 8, num_arms: int = 4,
                 noise: float = 0.05, seed: Optional[int] = None):
        self.observation_dim = context_dim
        self.num_actions = num_arms
        self.noise = noise
        self._rng = np.random.default_rng(seed)
        thetas = self._rng.normal(size=(num_arms, context_dim))
        self._thetas = thetas / np.linalg.norm(thetas, axis=1,
                                               keepdims=True)
        self._x = None

    def reset(self) -> np.ndarray:
        x = self._rng.normal(size=self.observation_dim)
        self._x = (x / np.linalg.norm(x)).astype(np.float32)
        return self._x

    def best_reward(self) -> float:
        return float(np.max(self._thetas @ self._x))

    def step(self, action: int):
        payoff = float(self._thetas[int(action)] @ self._x
                       + self._rng.normal(scale=self.noise))
        return self.reset(), payoff, True, {}


ENV_REGISTRY = {
    "CartPole-v1": CartPoleEnv,
    "StatelessGuess": StatelessGuessEnv,
    "Pendulum-v1": PendulumEnv,
    "LinearBandit": LinearBanditEnv,
}


def make_env(env: Any, env_config: Optional[dict] = None) -> Env:
    env_config = env_config or {}
    if isinstance(env, str):
        return ENV_REGISTRY[env](**env_config)
    if isinstance(env, type):
        return env(**env_config)
    if callable(env):
        return env(env_config)
    raise ValueError(f"cannot construct env from {env!r}")
