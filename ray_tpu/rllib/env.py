"""Built-in environments (pure numpy, no gym dependency).

Reference: rllib/env/ (VectorEnv, MultiAgentEnv wrappers). The env API
is gym-classic: reset() -> obs, step(a) -> (obs, reward, done, info).
CartPole uses the standard Barto-Sutton-Anderson dynamics; StatelessGuess
is a one-step env where the optimal policy is learnable in seconds (used
by tests as a fast learning-progress oracle).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class Env:
    observation_dim: int = 0
    num_actions: int = 0

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, dict]:
        raise NotImplementedError

    def seed(self, seed: int) -> None:
        self._rng = np.random.default_rng(seed)


class CartPoleEnv(Env):
    """Classic cart-pole balancing, 200-step episodes."""

    observation_dim = 4
    num_actions = 2

    def __init__(self, max_steps: int = 200, seed: Optional[int] = None):
        self.max_steps = max_steps
        self._rng = np.random.default_rng(seed)
        self._state = None
        self._t = 0

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._t = 0
        return self._state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = 10.0 if action == 1 else -10.0
        gravity, masscart, masspole = 9.8, 1.0, 0.1
        total_mass = masscart + masspole
        length = 0.5
        polemass_length = masspole * length
        tau = 0.02
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + polemass_length * theta_dot ** 2 * sintheta
                ) / total_mass
        thetaacc = (gravity * sintheta - costheta * temp) / (
            length * (4.0 / 3.0 - masspole * costheta ** 2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + tau * x_dot
        x_dot = x_dot + tau * xacc
        theta = theta + tau * theta_dot
        theta_dot = theta_dot + tau * thetaacc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._t += 1
        done = bool(abs(x) > 2.4 or abs(theta) > 0.2095
                    or self._t >= self.max_steps)
        return self._state.astype(np.float32), 1.0, done, {}


class StatelessGuessEnv(Env):
    """One-step env: obs is a random one-hot; reward 1 iff the action
    matches the hot index. Optimal return = 1.0; random = 1/num_actions."""

    def __init__(self, num_actions: int = 4, seed: Optional[int] = None):
        self.num_actions = num_actions
        self.observation_dim = num_actions
        self._rng = np.random.default_rng(seed)
        self._target = 0

    def reset(self) -> np.ndarray:
        self._target = int(self._rng.integers(self.num_actions))
        obs = np.zeros(self.num_actions, dtype=np.float32)
        obs[self._target] = 1.0
        return obs

    def step(self, action: int):
        reward = 1.0 if int(action) == self._target else 0.0
        return self.reset(), reward, True, {}


ENV_REGISTRY = {
    "CartPole-v1": CartPoleEnv,
    "StatelessGuess": StatelessGuessEnv,
}


def make_env(env: Any, env_config: Optional[dict] = None) -> Env:
    env_config = env_config or {}
    if isinstance(env, str):
        return ENV_REGISTRY[env](**env_config)
    if isinstance(env, type):
        return env(**env_config)
    if callable(env):
        return env(env_config)
    raise ValueError(f"cannot construct env from {env!r}")
