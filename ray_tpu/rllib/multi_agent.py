"""Multi-agent RL: MultiAgentEnv + policy mapping + trainer.

Reference behavior: rllib's multi-agent API (rllib/env/multi_agent_env.py,
the `multiagent` config of trainer.py: `policies` dict +
`policy_mapping_fn`, per-policy SampleBatches, independent or shared
policies). The env speaks dicts keyed by agent id; "__all__" in the done
dict ends the episode.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.sample_batch import SampleBatch


class MultiAgentEnv:
    """Dict-keyed env API (reference: rllib/env/multi_agent_env.py)."""

    agent_ids: Tuple[str, ...] = ()
    observation_dim: int = 0
    num_actions: int = 0

    def reset(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, int]) -> Tuple[
            Dict[str, np.ndarray], Dict[str, float], Dict[str, bool],
            Dict[str, dict]]:
        raise NotImplementedError

    def seed(self, seed: int) -> None:
        self._rng = np.random.default_rng(seed)


class TwoStepGuessEnv(MultiAgentEnv):
    """Two agents, each shown its own one-hot target; reward 1 for
    matching it, plus a 0.5 team bonus when BOTH match — learnable in
    seconds, with a cooperative component (the multi-agent analogue of
    StatelessGuessEnv)."""

    agent_ids = ("a0", "a1")

    def __init__(self, num_actions: int = 3, seed: Optional[int] = None):
        self.num_actions = num_actions
        self.observation_dim = num_actions
        self._rng = np.random.default_rng(seed)
        self._targets: Dict[str, int] = {}

    def reset(self) -> Dict[str, np.ndarray]:
        obs = {}
        for aid in self.agent_ids:
            t = int(self._rng.integers(self.num_actions))
            self._targets[aid] = t
            one_hot = np.zeros(self.num_actions, np.float32)
            one_hot[t] = 1.0
            obs[aid] = one_hot
        return obs

    def step(self, actions: Dict[str, int]):
        hits = {aid: int(actions[aid]) == self._targets[aid]
                for aid in self.agent_ids}
        bonus = 0.5 if all(hits.values()) else 0.0
        rewards = {aid: (1.0 if hits[aid] else 0.0) + bonus
                   for aid in self.agent_ids}
        dones = {aid: True for aid in self.agent_ids}
        dones["__all__"] = True
        return self.reset(), rewards, dones, {aid: {} for aid
                                              in self.agent_ids}


class MultiAgentRolloutWorker:
    """Env + a policy map; produces one SampleBatch PER POLICY, each
    postprocessed by its own policy (reference:
    rllib/evaluation/sampler.py multi-agent episode collection)."""

    def __init__(self, env: Any, policies: Dict[str, tuple],
                 policy_mapping_fn: Callable[[str], str],
                 env_config: Optional[dict] = None,
                 worker_index: int = 0):
        self.env = env(**(env_config or {})) if isinstance(env, type) \
            else env
        self.policy_mapping_fn = policy_mapping_fn
        self.policies: Dict[str, Any] = {}
        for pid, (cls, cfg) in policies.items():
            cfg = dict(cfg or {})
            cfg["seed"] = cfg.get("seed", 0) + worker_index * 1000
            self.policies[pid] = cls(self.env.observation_dim,
                                     self.env.num_actions, cfg)
        self._obs = self.env.reset()
        self._episode_reward = 0.0
        self.episode_rewards: List[float] = []

    def sample(self, num_steps: int) -> Dict[str, SampleBatch]:
        # Transitions accumulate PER AGENT so each agent's rows form one
        # contiguous trajectory; interleaving two agents' rows into a
        # single stream would let return-to-go/GAE postprocessing
        # bootstrap one agent's advantages from the other's rewards
        # (reference: per-agent episode collection in
        # rllib/evaluation/sampler.py).
        cols: Dict[str, Dict[str, list]] = {}  # agent_id -> columns
        for _ in range(num_steps):
            actions: Dict[str, int] = {}
            extras_by_agent: Dict[str, dict] = {}
            for aid, obs in self._obs.items():
                pid = self.policy_mapping_fn(aid)
                acts, extras = self.policies[pid].compute_actions(obs)
                actions[aid] = int(acts[0])
                extras_by_agent[aid] = extras
            next_obs, rewards, dones, _ = self.env.step(actions)
            for aid, obs in self._obs.items():
                c = cols.setdefault(aid, {})
                c.setdefault(sb.OBS, []).append(obs)
                c.setdefault(sb.ACTIONS, []).append(actions[aid])
                c.setdefault(sb.REWARDS, []).append(rewards[aid])
                c.setdefault(sb.DONES, []).append(dones.get(aid, False))
                c.setdefault(sb.NEXT_OBS, []).append(
                    next_obs.get(aid, obs))
                for k, v in extras_by_agent[aid].items():
                    c.setdefault(k, []).append(np.asarray(v)[0])
            self._episode_reward += float(np.mean(list(rewards.values())))
            if dones.get("__all__", False):
                self.episode_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs = self.env.reset()
            else:
                self._obs = next_obs
        per_policy: Dict[str, List[SampleBatch]] = {}
        for aid, c in cols.items():
            pid = self.policy_mapping_fn(aid)
            batch = SampleBatch({k: np.asarray(v) for k, v in c.items()})
            per_policy.setdefault(pid, []).append(
                self.policies[pid].postprocess_trajectory(batch))
        return {pid: SampleBatch.concat_samples(parts)
                for pid, parts in per_policy.items()}

    def learn_on_batches(self, batches: Dict[str, SampleBatch]
                         ) -> Dict[str, Dict[str, float]]:
        return {pid: self.policies[pid].learn_on_batch(batch)
                for pid, batch in batches.items()}

    def get_weights(self) -> Dict[str, Any]:
        return {pid: p.get_weights() for pid, p in self.policies.items()}

    def set_weights(self, weights: Dict[str, Any]) -> None:
        for pid, w in weights.items():
            self.policies[pid].set_weights(w)

    def get_metrics(self) -> Dict[str, Any]:
        rewards = self.episode_rewards[-100:]
        return {
            "episodes_total": len(self.episode_rewards),
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else float("nan"),
        }


class MultiAgentTrainer:
    """Synchronous multi-agent on-policy loop: parallel dict-of-batches
    rollouts -> per-policy learn on the local worker -> broadcast
    (reference: trainer.py multiagent config + the standard execution
    plan)."""

    def __init__(self, config: Optional[dict] = None, env: Any = None):
        cfg = {
            "env": None,
            "env_config": {},
            "num_workers": 2,
            "train_batch_size": 256,
            "policies": None,           # {policy_id: (policy_cls, cfg)}
            "policy_mapping_fn": None,  # agent_id -> policy_id
            "seed": 0,
        }
        cfg.update(config or {})
        if env is not None:
            cfg["env"] = env
        if cfg["env"] is None or not cfg["policies"]:
            raise ValueError("env and policies are required")
        if cfg["policy_mapping_fn"] is None:
            if len(cfg["policies"]) > 1:
                # silently routing every agent to one of several
                # configured policies would leave the rest untrained
                raise ValueError(
                    "policy_mapping_fn is required when more than one "
                    "policy is configured")
            first = next(iter(cfg["policies"]))
            cfg["policy_mapping_fn"] = lambda aid: first
        self.config = cfg
        self.local_worker = MultiAgentRolloutWorker(
            cfg["env"], cfg["policies"], cfg["policy_mapping_fn"],
            cfg["env_config"], worker_index=0)
        remote_cls = ray_tpu.remote(num_cpus=0.5)(MultiAgentRolloutWorker)
        self.remote_workers = [
            remote_cls.remote(cfg["env"], cfg["policies"],
                              cfg["policy_mapping_fn"], cfg["env_config"],
                              worker_index=i + 1)
            for i in range(cfg["num_workers"])]
        self._sync()
        self._iteration = 0
        self._timesteps_total = 0

    def _sync(self) -> None:
        weights = ray_tpu.put(self.local_worker.get_weights())
        ray_tpu.get([w.set_weights.remote(weights)
                     for w in self.remote_workers])

    def train(self) -> Dict[str, Any]:
        per_worker = max(1, self.config["train_batch_size"]
                         // max(len(self.remote_workers), 1))
        dicts = ray_tpu.get([w.sample.remote(per_worker)
                             for w in self.remote_workers]) \
            if self.remote_workers else [self.local_worker.sample(
                per_worker)]
        merged: Dict[str, List[SampleBatch]] = {}
        for d in dicts:
            for pid, batch in d.items():
                merged.setdefault(pid, []).append(batch)
        batches = {pid: SampleBatch.concat_samples(parts)
                   for pid, parts in merged.items()}
        self._timesteps_total += sum(b.count for b in batches.values())
        stats = self.local_worker.learn_on_batches(batches)
        self._sync()
        self._iteration += 1
        metrics = ray_tpu.get([w.get_metrics.remote()
                               for w in self.remote_workers]) \
            if self.remote_workers else [self.local_worker.get_metrics()]
        rewards = [m["episode_reward_mean"] for m in metrics
                   if not np.isnan(m["episode_reward_mean"])]
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._timesteps_total,
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else float("nan"),
            "info": {"learner": stats},
        }

    def get_policy(self, policy_id: str):
        return self.local_worker.policies[policy_id]

    def save_checkpoint(self) -> dict:
        return {"weights": self.local_worker.get_weights(),
                "iteration": self._iteration}

    def restore(self, checkpoint: dict) -> None:
        self.local_worker.set_weights(checkpoint["weights"])
        self._iteration = checkpoint["iteration"]
        self._sync()

    def stop(self) -> None:
        for w in self.remote_workers:
            ray_tpu.kill(w)
        self.remote_workers = []
