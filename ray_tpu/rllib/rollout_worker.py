"""RolloutWorker + WorkerSet + ReplayBuffer.

Reference: rllib/evaluation/rollout_worker.py (env+policy pair that
produces SampleBatches), worker_set.py (local learner + remote actor
fleet), execution/replay_ops.py (replay buffer).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.sample_batch import SampleBatch


class RolloutWorker:
    def __init__(self, env: Any, policy_cls, policy_config: Optional[dict]
                 = None, env_config: Optional[dict] = None,
                 worker_index: int = 0):
        self.env = make_env(env, env_config)
        cfg = dict(policy_config or {})
        cfg["seed"] = cfg.get("seed", 0) + worker_index * 1000
        self._continuous = bool(getattr(self.env, "action_dim", 0))
        if self._continuous:  # bounds flow env -> policy config
            cfg.setdefault("action_low", self.env.action_low)
            cfg.setdefault("action_high", self.env.action_high)
            self.policy = policy_cls(self.env.observation_dim,
                                     self.env.action_dim, cfg)
        else:
            self.policy = policy_cls(self.env.observation_dim,
                                     self.env.num_actions, cfg)
        self.worker_index = worker_index
        self._obs = self.env.reset()
        self._episode_reward = 0.0
        self._episode_len = 0
        self.episode_rewards: List[float] = []
        self.episode_lengths: List[int] = []

    def sample(self, num_steps: int) -> SampleBatch:
        cols: Dict[str, list] = {k: [] for k in (
            sb.OBS, sb.ACTIONS, sb.REWARDS, sb.DONES, sb.NEXT_OBS)}
        extra_cols: Dict[str, list] = {}
        for _ in range(num_steps):
            actions, extras = self.policy.compute_actions(self._obs)
            if self._continuous:
                action = np.asarray(actions[0], np.float32)
            else:
                action = int(actions[0])
            next_obs, reward, done, _ = self.env.step(action)
            cols[sb.OBS].append(self._obs)
            cols[sb.ACTIONS].append(action)
            cols[sb.REWARDS].append(reward)
            cols[sb.DONES].append(done)
            cols[sb.NEXT_OBS].append(next_obs)
            for k, v in extras.items():
                extra_cols.setdefault(k, []).append(np.asarray(v)[0])
            self._episode_reward += reward
            self._episode_len += 1
            if done:
                self.episode_rewards.append(self._episode_reward)
                self.episode_lengths.append(self._episode_len)
                self._episode_reward = 0.0
                self._episode_len = 0
                self._obs = self.env.reset()
            else:
                self._obs = next_obs
        batch = SampleBatch(
            {k: np.asarray(v) for k, v in {**cols, **extra_cols}.items()})
        return self.policy.postprocess_trajectory(batch)

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        return self.policy.learn_on_batch(batch)

    def get_weights(self):
        return self.policy.get_weights()

    def set_weights(self, weights) -> None:
        self.policy.set_weights(weights)

    def get_metrics(self) -> Dict[str, Any]:
        rewards = self.episode_rewards[-100:]
        lengths = self.episode_lengths[-100:]
        return {
            "episodes_total": len(self.episode_rewards),
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else float("nan"),
            "episode_len_mean": float(np.mean(lengths)) if lengths
            else float("nan"),
        }


class WorkerSet:
    """Local learner worker + remote sampler actors (reference:
    rllib/evaluation/worker_set.py)."""

    def __init__(self, env: Any, policy_cls, num_workers: int = 2,
                 policy_config: Optional[dict] = None,
                 env_config: Optional[dict] = None,
                 remote_args: Optional[dict] = None):
        self.local_worker = RolloutWorker(env, policy_cls, policy_config,
                                          env_config, worker_index=0)
        remote_cls = ray_tpu.remote(**(remote_args or {"num_cpus": 0.5}))(
            RolloutWorker)
        self.remote_workers = [
            remote_cls.remote(env, policy_cls, policy_config, env_config,
                              worker_index=i + 1)
            for i in range(num_workers)]

    def sample_parallel(self, steps_per_worker: int) -> SampleBatch:
        return SampleBatch.concat_samples(
            self.sample_parallel_batches(steps_per_worker))

    def sample_parallel_batches(self, steps_per_worker: int
                                ) -> list:
        """Per-worker fragments, NOT concatenated — algorithms whose math
        scans over time within a trajectory (V-trace) must not see two
        unrelated fragments glued together."""
        if not self.remote_workers:
            return [self.local_worker.sample(steps_per_worker)]
        return ray_tpu.get([w.sample.remote(steps_per_worker)
                            for w in self.remote_workers])

    def sync_weights(self) -> None:
        weights = ray_tpu.put(self.local_worker.get_weights())
        ray_tpu.get([w.set_weights.remote(weights)
                     for w in self.remote_workers])

    def remote_metrics(self) -> List[Dict[str, Any]]:
        if not self.remote_workers:
            return [self.local_worker.get_metrics()]
        return ray_tpu.get([w.get_metrics.remote()
                            for w in self.remote_workers])

    def stop(self) -> None:
        for w in self.remote_workers:
            ray_tpu.kill(w)
        self.remote_workers = []


class ReplayBuffer:
    """Uniform FIFO replay (reference: rllib/execution/replay_buffer.py)."""

    def __init__(self, capacity: int = 50_000, seed: int = 0):
        self.capacity = capacity
        self._cols: Optional[Dict[str, np.ndarray]] = None
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def add_batch(self, batch: SampleBatch) -> None:
        n = batch.count
        if n == 0:
            return
        if self._cols is None:
            self._cols = {
                k: np.zeros((self.capacity,) + np.asarray(v).shape[1:],
                            dtype=np.asarray(v).dtype)
                for k, v in batch.items()}
        for k, v in batch.items():
            v = np.asarray(v)
            idx = (self._next + np.arange(n)) % self.capacity
            self._cols[k][idx] = v
        self._next = (self._next + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def __len__(self) -> int:
        return self._size

    def sample(self, batch_size: int) -> SampleBatch:
        idx = self._rng.integers(self._size, size=batch_size)
        return SampleBatch({k: v[idx] for k, v in self._cols.items()})
