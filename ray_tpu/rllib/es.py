"""Evolution strategies trainers: ES and ARS.

Reference behavior: rllib/agents/es/ (OpenAI-ES: antithetic Gaussian
perturbations, centered-rank fitness shaping) and rllib/agents/ars/
(Augmented Random Search: top-k directions, std-of-returns step-size
normalization). Both are embarrassingly parallel: each perturbation's
fitness is one episode rollout, fanned out as ray_tpu tasks — the same
shape the reference runs across a cluster.

The evaluated policy is a deterministic linear/MLP over numpy params —
ES needs only a flat parameter vector and a fitness function.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env


def _policy_sizes(obs_dim: int, num_actions: int,
                  hidden: Tuple[int, ...]) -> List[Tuple[int, int]]:
    dims = (obs_dim, *hidden, num_actions)
    return list(zip(dims[:-1], dims[1:]))


def _num_params(sizes: List[Tuple[int, int]]) -> int:
    return sum(fi * fo + fo for fi, fo in sizes)


def _act(flat: np.ndarray, sizes: List[Tuple[int, int]],
         obs: np.ndarray) -> int:
    """Deterministic forward pass from the flat param vector."""
    x = obs
    off = 0
    for i, (fi, fo) in enumerate(sizes):
        w = flat[off:off + fi * fo].reshape(fi, fo)
        off += fi * fo
        b = flat[off:off + fo]
        off += fo
        x = x @ w + b
        if i < len(sizes) - 1:
            x = np.tanh(x)
    return int(np.argmax(x))


def rollout_fitness(flat_params, sizes, env, env_config, num_episodes,
                    seed) -> float:
    """One perturbation's fitness: mean episode return. Runs as a remote
    task (reference: es/es.py Worker.do_rollouts)."""
    e = make_env(env, env_config)
    e.seed(seed)
    total = 0.0
    for ep in range(num_episodes):
        obs = e.reset()
        done = False
        while not done:
            obs, reward, done, _ = e.step(_act(flat_params, sizes, obs))
            total += reward
    return total / num_episodes


class ESTrainer:
    """OpenAI evolution strategies (reference: agents/es/es.py)."""

    _default_config: Dict[str, Any] = {
        "env": None,
        "env_config": {},
        "num_workers": 4,          # concurrent fitness tasks
        "episodes_per_perturbation": 1,
        "num_perturbations": 16,   # antithetic pairs -> 2x evaluations
        "noise_std": 0.05,
        "lr": 0.02,
        "hidden": (32,),
        "seed": 0,
    }

    def __init__(self, config: Optional[dict] = None, env: Any = None):
        self.config = dict(self._default_config)
        self.config.update(config or {})
        if env is not None:
            self.config["env"] = env
        if self.config["env"] is None:
            raise ValueError("config['env'] is required")
        probe = make_env(self.config["env"], self.config["env_config"])
        self.sizes = _policy_sizes(probe.observation_dim,
                                   probe.num_actions,
                                   tuple(self.config["hidden"]))
        self._rng = np.random.default_rng(self.config["seed"])
        self.theta = self._rng.normal(
            scale=0.1, size=_num_params(self.sizes)).astype(np.float64)
        self._iteration = 0
        self._timesteps_total = 0
        self._fitness_task = ray_tpu.remote(num_cpus=0.25)(rollout_fitness)

    # ------------------------------------------------------------- update
    def _evaluate(self, thetas: List[np.ndarray]) -> np.ndarray:
        """Fan fitness rollouts out as remote tasks, at most num_workers
        in flight (the reference's worker-fleet width, es.py Workers)."""
        eps = self.config["episodes_per_perturbation"]
        width = max(1, int(self.config["num_workers"]))
        seeds = self._rng.integers(2 ** 31, size=len(thetas))
        results: List[float] = [0.0] * len(thetas)
        in_flight: dict = {}
        i = 0
        while i < len(thetas) or in_flight:
            while i < len(thetas) and len(in_flight) < width:
                ref = self._fitness_task.remote(
                    thetas[i], self.sizes, self.config["env"],
                    self.config["env_config"], eps, int(seeds[i]))
                in_flight[ref] = i
                i += 1
            done, _ = ray_tpu.wait(list(in_flight), num_returns=1,
                                   timeout=None)
            for ref in done:
                results[in_flight.pop(ref)] = ray_tpu.get([ref])[0]
        return np.asarray(results, np.float64)

    def _step_direction(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        n = self.config["num_perturbations"]
        noise = self._rng.normal(size=(n, len(self.theta)))
        thetas = [self.theta + self.config["noise_std"] * e
                  for e in noise]
        thetas += [self.theta - self.config["noise_std"] * e
                   for e in noise]
        fitness = self._evaluate(thetas)
        return noise, fitness[:n], fitness[n:]

    def training_step(self) -> Dict[str, float]:
        noise, f_pos, f_neg = self._step_direction()
        n = len(noise)
        # centered-rank fitness shaping (reference: es/utils.py
        # compute_centered_ranks)
        all_f = np.concatenate([f_pos, f_neg])
        ranks = np.empty(len(all_f))
        ranks[np.argsort(all_f)] = np.arange(len(all_f))
        ranks = ranks / (len(all_f) - 1) - 0.5
        shaped = ranks[:n] - ranks[n:]
        grad = (shaped[:, None] * noise).mean(axis=0) \
            / self.config["noise_std"]
        self.theta = self.theta + self.config["lr"] * grad
        return {"fitness_mean": float(all_f.mean()),
                "fitness_max": float(all_f.max())}

    # --------------------------------------------------------- Trainable
    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        stats = self.training_step()
        self._iteration += 1
        reward = self._evaluate([self.theta])[0]
        return {
            "training_iteration": self._iteration,
            "episode_reward_mean": float(reward),
            "time_this_iter_s": time.perf_counter() - t0,
            "info": {"learner": stats},
        }

    def compute_single_action(self, obs) -> int:
        return _act(self.theta, self.sizes, np.asarray(obs, np.float64))

    def save_checkpoint(self) -> dict:
        return {"theta": self.theta.copy(),
                "iteration": self._iteration}

    def restore(self, checkpoint: dict) -> None:
        self.theta = np.asarray(checkpoint["theta"]).copy()
        self._iteration = checkpoint["iteration"]

    def stop(self) -> None:
        pass


class ARSTrainer(ESTrainer):
    """Augmented random search (reference: agents/ars/ars.py): keep the
    top-k directions by max(f+, f-) and normalize the step by the std of
    their returns."""

    _default_config = {
        **ESTrainer._default_config,
        "top_directions": 8,
        "noise_std": 0.05,
        "lr": 0.02,
    }

    def training_step(self) -> Dict[str, float]:
        noise, f_pos, f_neg = self._step_direction()
        k = min(self.config["top_directions"], len(noise))
        best = np.argsort(np.maximum(f_pos, f_neg))[::-1][:k]
        f_p, f_n = f_pos[best], f_neg[best]
        sigma_r = np.concatenate([f_p, f_n]).std() + 1e-8
        grad = ((f_p - f_n)[:, None] * noise[best]).mean(axis=0)
        self.theta = self.theta + self.config["lr"] / sigma_r * grad
        all_f = np.concatenate([f_pos, f_neg])
        return {"fitness_mean": float(all_f.mean()),
                "fitness_max": float(all_f.max()),
                "sigma_r": float(sigma_r)}
