"""Orbax checkpointing for model/train state.

The TPU-idiomatic checkpointer: async-capable, sharding-aware saves of
param/optimizer pytrees (the role torch.save + TorchCheckpoint play in
the reference's train stack, done the JAX way). Works for any pytree —
the flagship transformer's (params, opt_state) included — and restores
onto the current mesh/sharding layout.

    from ray_tpu.models.checkpoint import CheckpointManager

    ckpt = CheckpointManager("/tmp/run1", max_to_keep=3)
    ckpt.save(step, {"params": params, "opt_state": opt_state})
    state = ckpt.restore_latest()       # or .restore(step)
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import jax


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: Optional[int] = 3,
                 create: bool = True):
        """create=False makes a read-side manager: a missing directory
        raises instead of silently materializing an empty checkpoint
        tree (a typo'd restore path must fail loudly)."""
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        if create:
            os.makedirs(self.directory, exist_ok=True)
        elif not os.path.isdir(self.directory):
            raise FileNotFoundError(
                f"no checkpoint directory at {self.directory}")
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=create),
        )

    # ---------------------------------------------------------------- save
    def save(self, step: int, state: Any, wait: bool = True) -> None:
        """Checkpoint a pytree at `step`; trims beyond max_to_keep."""
        import orbax.checkpoint as ocp

        self._manager.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._manager.wait_until_finished()

    # ------------------------------------------------------------- restore
    def restore(self, step: int, like: Any = None) -> Any:
        """Restore the pytree saved at `step`. Pass `like` (a pytree of
        arrays with the target shardings/dtypes, e.g. a freshly-init'd
        train state) to place restored arrays straight onto the current
        mesh layout."""
        import orbax.checkpoint as ocp
        from jax.sharding import NamedSharding, PartitionSpec

        if like is not None:
            # one mesh governs the layout: leaves the init left
            # uncommitted (optimizer scalars) restore REPLICATED on it —
            # a committed single-device scalar next to mesh-sharded
            # params would poison the next jitted step
            mesh = None
            for leaf in jax.tree.leaves(like):
                s = getattr(leaf, "sharding", None)
                if isinstance(s, NamedSharding):
                    mesh = s.mesh
                    break

            def as_abstract(x):
                if hasattr(x, "shape") and hasattr(x, "dtype"):
                    sharding = getattr(x, "sharding", None)
                    if (mesh is not None
                            and not isinstance(sharding, NamedSharding)):
                        sharding = NamedSharding(mesh, PartitionSpec())
                    return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                sharding=sharding)
                return x

            target = jax.tree.map(as_abstract, like)
            return self._manager.restore(
                step, args=ocp.args.StandardRestore(target))
        return self._manager.restore(
            step, args=ocp.args.StandardRestore())

    def restore_latest(self, like: Any = None) -> Optional[Any]:
        step = self.latest_step()
        if step is None:
            return None
        return self.restore(step, like)

    # ------------------------------------------------------------ metadata
    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def all_steps(self) -> List[int]:
        return sorted(self._manager.all_steps())

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()


def save_train_state(directory: str, step: int, params: Any,
                     opt_state: Any = None,
                     extra: Optional[Dict[str, Any]] = None) -> None:
    """One-shot convenience around CheckpointManager for train loops."""
    ckpt = CheckpointManager(directory, max_to_keep=None)
    state: Dict[str, Any] = {"params": params}
    if opt_state is not None:
        state["opt_state"] = opt_state
    if extra:
        state.update(extra)
    try:
        ckpt.save(step, state)
    finally:
        ckpt.close()


def restore_train_state(directory: str, step: Optional[int] = None,
                        like: Any = None) -> Optional[Any]:
    ckpt = CheckpointManager(directory, max_to_keep=None, create=False)
    try:
        if step is None:
            return ckpt.restore_latest(like)
        return ckpt.restore(step, like)
    finally:
        ckpt.close()
