"""Flagship model family: decoder-only transformer (dense + MoE).

Pure-functional JAX: parameters are a pytree of arrays with a parallel
pytree of *logical axis names* (models/sharding rules in
parallel/mesh.py map those to mesh axes). Layers are stacked along a
leading axis and iterated with ``lax.scan`` so compile time is O(1) in
depth and the pipeline path can shard the same stack over ``pp``.

Architecture: RMSNorm, rotary embeddings, GQA attention via
ops.flash_attention, SwiGLU MLP, optional top-2 MoE layers
(GShard-style capacity-bounded einsum dispatch; experts shard over the
``dp`` mesh axis = expert parallelism).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops.attention import flash_attention
from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies, swiglu


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32000
    hidden: int = 512
    layers: int = 4
    heads: int = 8
    kv_heads: int = 8
    intermediate: int = 1408
    max_seq: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    # MoE: every `moe_every`-th layer is sparse when num_experts > 0
    num_experts: int = 0
    experts_per_token: int = 2
    moe_every: int = 2
    capacity_factor: float = 1.25
    # grouped dispatch: when >0 and it divides B*S, tokens route in
    # independent groups of this size with per-group capacity, scanned
    # under jax.checkpoint — the GShard [tokens, experts, capacity]
    # dispatch/combine one-hots then scale with the GROUP, not the
    # batch (at B16-S2048-E8 ungrouped they are 5 GiB each and OOM a
    # 16 GB chip; 4096-token groups bound them to ~160 MB). Per-group
    # capacity is the standard GShard/Mixtral local-group semantics.
    moe_group_size: int = 0
    remat: bool = True
    # remat granularity when ``remat`` is on: "full" recomputes the whole
    # block in the backward (lowest memory, ~+1/3 matmul FLOPs); "dots"
    # saves weight-activation matmul outputs and recomputes only the
    # cheap elementwise ops (jax.checkpoint_policies.
    # dots_with_no_batch_dims_saveable — attention logits have batch
    # dims, so the [S, S] matrix is never saved). "dots" trades HBM for
    # FLOPs: use it when the batch that fits is compute-bound anyway.
    remat_policy: str = "full"
    tie_embeddings: bool = True
    # chunked cross-entropy: when >0 and it divides the sequence, the
    # loss projects to vocab one [B, chunk, V] slab at a time under
    # jax.checkpoint, so the fp32 [B, S, V] logits never materialize
    # (the dominant HBM allocation at large batch x vocab)
    logits_chunk: int = 0

    def __post_init__(self):
        # a typo'd policy silently measuring full remat would mislabel
        # an A/B data point (r05 review finding)
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(
                f"remat_policy must be 'full' or 'dots', "
                f"got {self.remat_policy!r}")
        if self.moe_group_size < 0:
            raise ValueError(
                f"moe_group_size must be >= 0, "
                f"got {self.moe_group_size}")

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads

    @classmethod
    def debug(cls, **kw) -> "ModelConfig":
        return cls(vocab_size=256, hidden=64, layers=2, heads=4, kv_heads=2,
                   intermediate=128, max_seq=128, dtype=jnp.float32, **kw)

    @classmethod
    def tiny_moe(cls, **kw) -> "ModelConfig":
        return cls(vocab_size=256, hidden=64, layers=2, heads=4, kv_heads=4,
                   intermediate=128, max_seq=128, num_experts=4,
                   dtype=jnp.float32, **kw)

    @classmethod
    def b1(cls) -> "ModelConfig":
        """~1.2B dense (llama-ish shape)."""
        return cls(vocab_size=32000, hidden=2048, layers=24, heads=16,
                   kv_heads=16, intermediate=5632, max_seq=4096)

    @classmethod
    def b7(cls) -> "ModelConfig":
        return cls(vocab_size=32000, hidden=4096, layers=32, heads=32,
                   kv_heads=32, intermediate=11008, max_seq=4096)


# -- parameter init + logical axes -----------------------------------------


def init_params(cfg: ModelConfig, key: jax.Array) -> Dict[str, Any]:
    k = jax.random.split(key, 13)
    h, hd, nl = cfg.hidden, cfg.head_dim, cfg.layers
    scale = h ** -0.5
    dt = cfg.dtype

    def norm_init(shape):
        return jnp.ones(shape, dtype=jnp.float32)

    params: Dict[str, Any] = {
        "embed": (jax.random.normal(k[0], (cfg.vocab_size, h)) * 0.02
                  ).astype(dt),
        "final_norm": norm_init((h,)),
        "layers": {
            "attn_norm": norm_init((nl, h)),
            "mlp_norm": norm_init((nl, h)),
            "wq": (jax.random.normal(k[1], (nl, h, cfg.heads * hd))
                   * scale).astype(dt),
            "wk": (jax.random.normal(k[2], (nl, h, cfg.kv_heads * hd))
                   * scale).astype(dt),
            "wv": (jax.random.normal(k[3], (nl, h, cfg.kv_heads * hd))
                   * scale).astype(dt),
            "wo": (jax.random.normal(k[4], (nl, cfg.heads * hd, h))
                   * scale).astype(dt),
        },
    }
    if not cfg.tie_embeddings:
        params["unembed"] = (jax.random.normal(k[5], (h, cfg.vocab_size))
                             * scale).astype(dt)
    dense = {
        "w_gate": (jax.random.normal(k[6], (nl, h, cfg.intermediate))
                   * scale).astype(dt),
        "w_up": (jax.random.normal(k[7], (nl, h, cfg.intermediate))
                 * scale).astype(dt),
        "w_down": (jax.random.normal(k[8], (nl, cfg.intermediate, h))
                   * (cfg.intermediate ** -0.5)).astype(dt),
    }
    params["layers"].update(dense)
    if cfg.num_experts > 0:
        e = cfg.num_experts
        params["layers"]["moe"] = {
            "router": (jax.random.normal(k[9], (nl, h, e)) * scale
                       ).astype(jnp.float32),
            "w_gate": (jax.random.normal(k[10], (nl, e, h, cfg.intermediate))
                       * scale).astype(dt),
            "w_up": (jax.random.normal(k[11], (nl, e, h, cfg.intermediate))
                     * scale).astype(dt),
            "w_down": (jax.random.normal(k[12], (nl, e, cfg.intermediate, h))
                       * (cfg.intermediate ** -0.5)).astype(dt),
        }
    return params


def logical_axes(cfg: ModelConfig) -> Dict[str, Any]:
    """Same-structure pytree of logical axis tuples, consumed by
    parallel.mesh.sharding_for."""
    axes: Dict[str, Any] = {
        "embed": ("vocab", "hidden"),
        "final_norm": ("hidden",),
        "layers": {
            "attn_norm": ("layers", "hidden"),
            "mlp_norm": ("layers", "hidden"),
            "wq": ("layers", "hidden", "heads"),
            "wk": ("layers", "hidden", "kv_heads"),
            "wv": ("layers", "hidden", "kv_heads"),
            "wo": ("layers", "heads", "hidden"),
            "w_gate": ("layers", "hidden", "mlp"),
            "w_up": ("layers", "hidden", "mlp"),
            "w_down": ("layers", "mlp", "hidden"),
        },
    }
    if not cfg.tie_embeddings:
        axes["unembed"] = ("hidden", "vocab")
    if cfg.num_experts > 0:
        axes["layers"]["moe"] = {
            "router": ("layers", "hidden", None),
            "w_gate": ("layers", "experts", "hidden", "mlp"),
            "w_up": ("layers", "experts", "hidden", "mlp"),
            "w_down": ("layers", "experts", "mlp", "hidden"),
        }
    return axes


# -- MoE ---------------------------------------------------------------------


def moe_layer(x: jax.Array, moe_params: Dict[str, jax.Array],
              cfg: ModelConfig) -> Tuple[jax.Array, jax.Array]:
    """Top-k capacity-bounded MoE (GShard-style einsum dispatch).

    x: [B, S, H] -> ([B, S, H], aux_loss scalar). With
    ``cfg.moe_group_size`` set, tokens route in independent scanned
    groups (see the config field's memory rationale); the aux loss is
    averaged over groups."""
    b, s, h = x.shape
    t = b * s
    g = cfg.moe_group_size
    xt = x.reshape(t, h)
    if g and t > g:
        if t % g == 0:
            n_groups = t // g
            # checkpoint per group: without it, the scan (and the
            # layer remat's backward recompute) stacks every group's
            # [g, E, C] dispatch residuals and reintroduces the
            # ungrouped peak
            group_fn = jax.checkpoint(
                lambda xg: _moe_tokens(xg, moe_params, cfg))

            def body(aux_sum, xg):
                out, aux = group_fn(xg)
                return aux_sum + aux, out

            aux_sum, outs = lax.scan(body, jnp.zeros((), jnp.float32),
                                     xt.reshape(n_groups, g, h))
            return outs.reshape(b, s, h), aux_sum / n_groups
        # same discipline as the logits_chunk fallback: dropping the
        # grouping silently would reintroduce the OOM-scale ungrouped
        # [T, E, capacity] dispatch tensors this feature exists to
        # prevent
        import logging

        logging.getLogger(__name__).warning(
            "moe_group_size=%d does not divide token count %d; "
            "falling back to UNGROUPED routing (dispatch tensors "
            "scale with the full batch — may OOM at large batch)",
            g, t)
    out, aux = _moe_tokens(xt, moe_params, cfg)
    return out.reshape(b, s, h), aux


def _moe_tokens(xt: jax.Array, moe_params: Dict[str, jax.Array],
                cfg: ModelConfig) -> Tuple[jax.Array, jax.Array]:
    """Route one token set [T, H] -> ([T, H], aux)."""
    t, h = xt.shape
    e = cfg.num_experts
    k = cfg.experts_per_token
    cap = max(1, int(cfg.capacity_factor * t * k / e))
    logits = jnp.einsum("th,he->te", xt.astype(jnp.float32),
                        moe_params["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = lax.top_k(probs, k)           # [T, k]
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)
    # load-balancing auxiliary loss (Switch Transformer eq. 4)
    me = probs.mean(axis=0)
    ce = jnp.zeros((e,), jnp.float32).at[gate_idx.reshape(-1)].add(
        1.0 / (t * k))
    aux = e * jnp.sum(me * ce)
    # position of each (token, choice) within its expert's capacity
    onehot = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)  # [T, k, E]
    flat = onehot.reshape(t * k, e)
    pos = (jnp.cumsum(flat, axis=0) - flat).reshape(t, k, e)
    within = (pos * onehot).sum(-1)                     # [T, k]
    keep = within < cap
    gate_vals = gate_vals * keep
    pos_idx = jnp.clip(within, 0, cap - 1).astype(jnp.int32)
    # dispatch tensor [T, E, C]
    dispatch = jnp.einsum(
        "tke,tkc->tec", onehot * keep[..., None],
        jax.nn.one_hot(pos_idx, cap, dtype=jnp.float32))
    combine = jnp.einsum("tke,tkc,tk->tec", onehot,
                         jax.nn.one_hot(pos_idx, cap, dtype=jnp.float32),
                         gate_vals)
    expert_in = jnp.einsum("tec,th->ech", dispatch,
                           xt.astype(jnp.float32)).astype(xt.dtype)
    expert_out = jax.vmap(
        lambda xi, wg, wu, wd: swiglu(xi, wg, wu, wd))(
        expert_in, moe_params["w_gate"], moe_params["w_up"],
        moe_params["w_down"])                           # [E, C, H]
    out = jnp.einsum("tec,ech->th", combine,
                     expert_out.astype(jnp.float32)).astype(xt.dtype)
    return out, aux


# -- transformer block -------------------------------------------------------


def attention_block(x, layer, cfg: ModelConfig, cos, sin,
                    attention_fn: Callable) -> jax.Array:
    b, s, h = x.shape
    hd = cfg.head_dim
    xn = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("bsh,hd->bsd", xn, layer["wq"]).reshape(
        b, s, cfg.heads, hd)
    k = jnp.einsum("bsh,hd->bsd", xn, layer["wk"]).reshape(
        b, s, cfg.kv_heads, hd)
    v = jnp.einsum("bsh,hd->bsd", xn, layer["wv"]).reshape(
        b, s, cfg.kv_heads, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    if cfg.kv_heads != cfg.heads:
        rep = cfg.heads // cfg.kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    attn = attention_fn(q, k, v)
    attn = attn.reshape(b, s, cfg.heads * hd)
    return x + jnp.einsum("bsd,dh->bsh", attn, layer["wo"])


def mlp_block(x, layer, layer_idx, cfg: ModelConfig) -> Tuple[jax.Array,
                                                              jax.Array]:
    xn = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    aux = jnp.zeros((), jnp.float32)
    if cfg.num_experts > 0 and "moe" in layer:
        is_moe = (layer_idx % cfg.moe_every) == (cfg.moe_every - 1)
        # lax.cond so only one branch's FLOPs run per layer (jnp.where
        # would execute both the MoE dispatch and the dense SwiGLU)
        out, aux = lax.cond(
            is_moe,
            lambda t: moe_layer(t, layer["moe"], cfg),
            lambda t: (swiglu(t, layer["w_gate"], layer["w_up"],
                              layer["w_down"]),
                       jnp.zeros((), jnp.float32)),
            xn)
    else:
        out = swiglu(xn, layer["w_gate"], layer["w_up"], layer["w_down"])
    return x + out, aux


def hidden_states(params: Dict[str, Any], tokens: jax.Array,
                  cfg: ModelConfig,
                  attention_fn: Optional[Callable] = None
                  ) -> Tuple[jax.Array, jax.Array]:
    """tokens [B, S] int32 -> (final hidden states [B, S, H], aux)."""
    if attention_fn is None:
        attention_fn = lambda q, k, v: flash_attention(q, k, v, True)  # noqa: E731
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq, cfg.rope_theta)
    x = jnp.take(params["embed"], tokens, axis=0)

    def block(carry, scanned):
        x, aux_sum = carry
        layer, idx = scanned
        x = attention_block(x, layer, cfg, cos, sin, attention_fn)
        x, aux = mlp_block(x, layer, idx, cfg)
        return (x, aux_sum + aux), None

    if cfg.remat:
        if cfg.remat_policy == "dots":
            block_fn = jax.checkpoint(
                block,
                policy=jax.checkpoint_policies.
                dots_with_no_batch_dims_saveable)
        else:
            block_fn = jax.checkpoint(block)
    else:
        block_fn = block
    (x, aux), _ = lax.scan(
        block_fn, (x, jnp.zeros((), jnp.float32)),
        (params["layers"], jnp.arange(cfg.layers)))
    return rms_norm(x, params["final_norm"], cfg.norm_eps), aux


def _unembed(params, cfg: ModelConfig):
    return (params["embed"].T if cfg.tie_embeddings
            else params["unembed"])


def forward(params: Dict[str, Any], tokens: jax.Array, cfg: ModelConfig,
            attention_fn: Optional[Callable] = None) -> Tuple[jax.Array,
                                                              jax.Array]:
    """tokens [B, S] int32 -> (logits [B, S, V] float32, aux_loss)."""
    x, aux = hidden_states(params, tokens, cfg, attention_fn)
    logits = jnp.einsum("bsh,hv->bsv", x.astype(jnp.float32),
                        _unembed(params, cfg).astype(jnp.float32))
    return logits, aux


def loss_fn(params, tokens, cfg: ModelConfig,
            attention_fn: Optional[Callable] = None) -> jax.Array:
    """Next-token cross entropy over tokens[:, :-1] -> tokens[:, 1:].

    When ``cfg.logits_chunk`` divides the sequence, the vocab
    projection + log-softmax run per sequence chunk under
    ``jax.checkpoint`` inside a scan, so the fp32 [B, S, V] logits
    tensor never materializes — at B32-S2048-V32k that tensor is
    2 x 7.8 GiB of HBM (fwd + grad), the allocation that capped the
    bench batch size (OOM trace in the r05 A/B). Backward recomputes
    one [B, C, V] chunk at a time."""
    x, aux = hidden_states(params, tokens[:, :-1], cfg, attention_fn)
    targets = tokens[:, 1:]
    unembed = _unembed(params, cfg)
    b, s, _ = x.shape
    chunk = cfg.logits_chunk
    if chunk and (s % chunk != 0 and s > chunk):
        # a non-dividing chunk would silently reintroduce the full
        # [B,S,V] fp32 logits — the OOM this feature exists to prevent
        import logging

        logging.getLogger(__name__).warning(
            "logits_chunk=%d does not divide sequence length %d; "
            "falling back to UNCHUNKED loss (full [B,S,V] fp32 logits "
            "materialize — may OOM at large batch x vocab)", chunk, s)
    if chunk and s % chunk == 0 and s > chunk:
        n_chunks = s // chunk

        def chunk_nll(x_c, t_c, emb):
            logits = jnp.einsum("bch,hv->bcv", x_c.astype(jnp.float32),
                                emb.astype(jnp.float32))
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(
                logp, t_c[..., None], axis=-1)[..., 0].sum()

        chunk_fn = jax.checkpoint(chunk_nll)
        xs = x.reshape(b, n_chunks, chunk, -1).swapaxes(0, 1)
        ts = targets.reshape(b, n_chunks, chunk).swapaxes(0, 1)

        def body(acc, inp):
            x_c, t_c = inp
            return acc + chunk_fn(x_c, t_c, unembed), None

        total, _ = lax.scan(body, jnp.zeros((), jnp.float32), (xs, ts))
        return total / (b * s) + 0.01 * aux
    logits = jnp.einsum("bsh,hv->bsv", x.astype(jnp.float32),
                        unembed.astype(jnp.float32))
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean() + 0.01 * aux
