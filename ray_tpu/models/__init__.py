
from ray_tpu.models.checkpoint import (  # noqa: F401
    CheckpointManager,
    restore_train_state,
    save_train_state,
)
