"""Sharded training steps for the model family.

Two builders over one model:

  build_train_step   GSPMD path (pp == 1): jit with NamedSharding
                     annotations; dp shards batch (fsdp optionally shards
                     params over dp), tp shards heads/mlp/vocab, sp runs
                     ring attention inside a partial shard_map over the
                     ``sp`` axis, experts shard over dp (= ep). XLA
                     inserts all collectives (scaling-book recipe).

  build_pipeline_train_step
                     pp > 1: the layer stack shards over ``pp`` and runs
                     the GPipe schedule (parallel/pipeline.py) inside a
                     shard_map manual over pp (dp/tp stay automatic).

Both return (step_fn, init_fn) where step_fn(params, opt_state, tokens)
-> (params, opt_state, metrics) is donate-safe and jit-compiled over the
given mesh.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import transformer as tfm
from ray_tpu.parallel.mesh import DEFAULT_RULES, fsdp_rules, spec_for
from ray_tpu.parallel.ring_attention import ring_attention

try:  # jax >= 0.8 top-level
    from jax import shard_map as _shard_map_fn

    def shard_map(f, mesh, in_specs, out_specs, **kw):
        return _shard_map_fn(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _legacy

    def shard_map(f, mesh, in_specs, out_specs, **kw):
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **kw)


def make_optimizer(learning_rate: float = 3e-4, weight_decay: float = 0.1,
                   b1: float = 0.9, b2: float = 0.95,
                   grad_clip: float = 1.0) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(learning_rate, b1=b1, b2=b2,
                    weight_decay=weight_decay),
    )


def param_shardings(cfg: tfm.ModelConfig, mesh: Mesh,
                    fsdp: bool = False) -> Dict[str, Any]:
    rules = fsdp_rules() if fsdp else DEFAULT_RULES
    axes = tfm.logical_axes(cfg)
    return jax.tree.map(
        lambda ax: NamedSharding(mesh, spec_for(ax, rules)), axes,
        is_leaf=lambda x: isinstance(x, tuple))


def _make_attention_fn(mesh: Mesh, cfg: tfm.ModelConfig,
                       sp_strategy: str = "ring"):
    """Sequence-parallel attention over sp when the mesh has an sp axis
    > 1, else the local flash kernel. Two sp strategies: "ring" (K/V
    rotation, O(1) memory, parallel/ring_attention.py) and "ulysses"
    (all-to-all head/seq swap, parallel/ulysses.py) — pick ulysses when
    heads >> sp and all-to-all bandwidth is plentiful."""
    sp = mesh.shape.get("sp", 1)
    if sp == 1:
        from ray_tpu.ops.attention import flash_attention

        return lambda q, k, v: flash_attention(q, k, v, True)
    if sp_strategy == "ulysses":
        from ray_tpu.parallel.ulysses import ulysses_attention

        sp_body = functools.partial(ulysses_attention, axis_name="sp",
                                    causal=True)
    elif sp_strategy == "ring":
        sp_body = functools.partial(ring_attention, axis_name="sp",
                                    causal=True)
    else:
        raise ValueError(f"unknown sp_strategy {sp_strategy!r}")

    def attn(q, k, v):
        body = sp_body
        f = shard_map(
            body, mesh,
            in_specs=(P("dp", "sp", "tp", None),) * 3,
            out_specs=P("dp", "sp", "tp", None),
            axis_names={"sp", "dp", "tp"},
        )
        return f(q, k, v)

    return attn


def build_train_step(cfg: tfm.ModelConfig, mesh: Mesh, *,
                     fsdp: bool = False,
                     optimizer: Optional[optax.GradientTransformation] = None,
                     sp_strategy: str = "ring",
                     ) -> Tuple[Callable, Callable]:
    """GSPMD data/tensor/sequence/expert-parallel train step (pp=1)."""
    optimizer = optimizer or make_optimizer()
    p_shard = param_shardings(cfg, mesh, fsdp=fsdp)
    tok_shard = NamedSharding(mesh, P("dp", None))
    attention_fn = _make_attention_fn(mesh, cfg, sp_strategy=sp_strategy)

    def init_fn(key):
        params = tfm.init_params(cfg, key)
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s), params, p_shard)
        opt_state = optimizer.init(params)
        return params, opt_state

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: tfm.loss_fn(p, tokens, cfg, attention_fn))(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        return params, opt_state, {"loss": loss, "grad_norm": gnorm}

    step_jit = jax.jit(
        step,
        in_shardings=(p_shard, None, tok_shard),
        out_shardings=(p_shard, None, None),
        donate_argnums=(0, 1),
    )
    return step_jit, init_fn


def build_forward(cfg: tfm.ModelConfig, mesh: Optional[Mesh] = None):
    """Jitted inference forward (the graft entry's single-chip fn)."""
    attention_fn = None
    if mesh is not None:
        attention_fn = _make_attention_fn(mesh, cfg)

    @jax.jit
    def fwd(params, tokens):
        logits, _ = tfm.forward(params, tokens, cfg, attention_fn)
        return logits

    return fwd


# -- pipeline path -----------------------------------------------------------


def build_pipeline_train_step(cfg: tfm.ModelConfig, mesh: Mesh, *,
                              num_microbatches: Optional[int] = None,
                              optimizer: Optional[
                                  optax.GradientTransformation] = None,
                              ) -> Tuple[Callable, Callable]:
    """pp > 1: layer stack sharded over ``pp``, GPipe schedule inside a
    shard_map; embed/unembed replicated across stages."""
    from ray_tpu.parallel.pipeline import pipeline_spmd

    pp = mesh.shape["pp"]
    assert cfg.layers % pp == 0, "pp must divide layers"
    # The GPipe stage_fn carries only the hidden activations, so the MoE
    # router's load-balancing aux loss cannot flow to the loss yet; fail
    # loudly rather than silently train without router balancing.
    assert cfg.num_experts == 0, (
        "MoE (num_experts > 0) is not supported on the pipeline path; "
        "use build_train_step (GSPMD) for MoE configs")
    optimizer = optimizer or make_optimizer()
    num_microbatches = num_microbatches or pp

    rules = dict(DEFAULT_RULES)
    p_shard = param_shardings(cfg, mesh)  # layers axis -> pp
    tok_shard = NamedSharding(mesh, P("dp", None))

    def init_fn(key):
        params = tfm.init_params(cfg, key)
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s), params, p_shard)
        opt_state = optimizer.init(params)
        return params, opt_state

    cos_sin = tfm.rope_frequencies(cfg.head_dim, cfg.max_seq,
                                   cfg.rope_theta)

    def stage_fn(stage_layers, x):
        # x: [mb, S, H]; stage_layers: layer stack slice of size L/pp
        from ray_tpu.ops.attention import flash_attention

        attention_fn = lambda q, k, v: flash_attention(q, k, v, True)  # noqa: E731

        def block(carry, scanned):
            x, = carry
            layer, idx = scanned
            x = tfm.attention_block(x, layer, cfg, cos_sin[0], cos_sin[1],
                                    attention_fn)
            x, _aux = tfm.mlp_block(x, layer, idx, cfg)
            return (x,), None

        n_local = jax.tree.leaves(stage_layers)[0].shape[0]
        stage = jax.lax.axis_index("pp")
        idxs = stage * n_local + jnp.arange(n_local)
        block_fn = jax.checkpoint(block) if cfg.remat else block
        (x,), _ = jax.lax.scan(block_fn, (x,), (stage_layers, idxs))
        return x

    def pipe_apply(layer_params, hidden):
        body = functools.partial(pipeline_spmd, stage_fn, axis_name="pp",
                                 num_microbatches=num_microbatches)
        # manual only over pp: specs may mention pp alone; dp/tp sharding
        # of the same arrays stays automatic inside the region
        layer_specs = jax.tree.map(
            lambda s: P(*[a if a == "pp" else None for a in
                          (s.spec + (None,) * 8)[:8]][: len(s.spec)]),
            p_shard["layers"],
            is_leaf=lambda x: isinstance(x, NamedSharding))
        f = shard_map(
            body, mesh,
            in_specs=(layer_specs, P()),
            out_specs=P(),
            axis_names={"pp"},
        )
        return f(layer_params, hidden)

    def loss(params, tokens):
        inp = tokens[:, :-1]
        x = jnp.take(params["embed"], inp, axis=0)
        x = pipe_apply(params["layers"], x)
        x = tfm.rms_norm(x, params["final_norm"], cfg.norm_eps)
        unembed = (params["embed"].T if cfg.tie_embeddings
                   else params["unembed"])
        logits = jnp.einsum("bsh,hv->bsv", x.astype(jnp.float32),
                            unembed.astype(jnp.float32))
        targets = tokens[:, 1:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return nll.mean()

    def step(params, opt_state, tokens):
        l, grads = jax.value_and_grad(loss)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": l,
                                   "grad_norm": optax.global_norm(grads)}

    step_jit = jax.jit(
        step,
        in_shardings=(p_shard, None, tok_shard),
        out_shardings=(p_shard, None, None),
        donate_argnums=(0, 1),
    )
    return step_jit, init_fn
