"""Vision Transformer family — the image-model counterpart of the
flagship text transformer.

The reference's model families live in rllib/models (FCNet/VisionNet
catalogs) and its libraries train arbitrary user torch/TF models; this
build ships a first-class TPU-native image family: ViT with the same hot
ops as the text model (flash attention from ops/attention.py, MXU-tiled
matmuls, bf16 by default), so the whole model zoo shares one kernel set.

Functional style matching models/transformer.py: init_params(cfg, key)
-> pytree; forward(params, images, cfg) -> logits; loss_fn for training;
logical_axes for pjit sharding (dp over batch, tp over heads/mlp)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention


@dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    num_classes: int = 1000
    hidden: int = 384
    layers: int = 6
    heads: int = 6
    intermediate: int = 1536
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    pool: str = "cls"  # "cls" | "mean"

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads

    @classmethod
    def debug(cls, **kw) -> "ViTConfig":
        return cls(image_size=32, patch_size=8, num_classes=10, hidden=64,
                   layers=2, heads=4, intermediate=128, dtype=jnp.float32,
                   **kw)

    @classmethod
    def base(cls) -> "ViTConfig":
        return cls(hidden=768, layers=12, heads=12, intermediate=3072)


def init_params(cfg: ViTConfig, key: jax.Array) -> Dict[str, Any]:
    keys = jax.random.split(key, 6 + cfg.layers)
    d = cfg.hidden
    patch_dim = cfg.patch_size * cfg.patch_size * cfg.channels
    scale = d ** -0.5
    params: Dict[str, Any] = {
        "patch_w": (jax.random.normal(keys[0], (patch_dim, d))
                    * (patch_dim ** -0.5)).astype(cfg.dtype),
        "patch_b": jnp.zeros(d, cfg.dtype),
        "pos": (jax.random.normal(keys[1], (cfg.num_patches + 1, d))
                * 0.02).astype(cfg.dtype),
        "cls": (jax.random.normal(keys[2], (d,)) * 0.02).astype(cfg.dtype),
        "norm_out": jnp.ones(d, cfg.dtype),
        "head_w": (jax.random.normal(keys[3], (d, cfg.num_classes))
                   * scale).astype(cfg.dtype),
        "head_b": jnp.zeros(cfg.num_classes, cfg.dtype),
        "blocks": [],
    }
    for i in range(cfg.layers):
        k1, k2, k3, k4 = jax.random.split(keys[6 + i], 4)
        params["blocks"].append({
            "norm1": jnp.ones(d, cfg.dtype),
            "norm2": jnp.ones(d, cfg.dtype),
            "wqkv": (jax.random.normal(k1, (d, 3 * d)) * scale
                     ).astype(cfg.dtype),
            "wo": (jax.random.normal(k2, (d, d)) * scale).astype(cfg.dtype),
            "w1": (jax.random.normal(k3, (d, cfg.intermediate)) * scale
                   ).astype(cfg.dtype),
            "w2": (jax.random.normal(k4, (cfg.intermediate, d))
                   * (cfg.intermediate ** -0.5)).astype(cfg.dtype),
        })
    return params


def logical_axes(cfg: ViTConfig) -> Dict[str, Any]:
    """Sharding hints: tp splits heads (qkv/o) and the MLP intermediate,
    mirroring models/transformer.py logical_axes."""
    block = {
        "norm1": (None,), "norm2": (None,),
        "wqkv": (None, "tp"), "wo": ("tp", None),
        "w1": (None, "tp"), "w2": ("tp", None),
    }
    return {
        "patch_w": (None, None), "patch_b": (None,),
        "pos": (None, None), "cls": (None,),
        "norm_out": (None,),
        "head_w": (None, "tp"), "head_b": ("tp",),
        "blocks": [dict(block) for _ in range(cfg.layers)],
    }


def _layer_norm(x, weight, eps):
    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * weight


def patchify(images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """[B, H, W, C] -> [B, N, patch_dim] without conv: reshape+transpose
    keeps it a pure layout op; the projection matmul hits the MXU."""
    b, h, w, c = images.shape
    p = cfg.patch_size
    x = images.reshape(b, h // p, p, w // p, p, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (h // p) * (w // p), p * p * c)


def forward(params: Dict[str, Any], images: jax.Array,
            cfg: ViTConfig) -> jax.Array:
    """images [B, H, W, C] float -> logits [B, num_classes]."""
    x = patchify(images.astype(cfg.dtype), cfg)
    x = x @ params["patch_w"] + params["patch_b"]
    b = x.shape[0]
    seq = x.shape[1] + 1  # patches + CLS
    cls = jnp.broadcast_to(params["cls"], (b, 1, cfg.hidden))
    x = jnp.concatenate([cls, x], axis=1) + params["pos"][None, :seq]
    for block in params["blocks"]:
        h = _layer_norm(x, block["norm1"], cfg.norm_eps)
        qkv = h @ block["wqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        s = q.shape[1]
        q = q.reshape(b, s, cfg.heads, cfg.head_dim)
        k = k.reshape(b, s, cfg.heads, cfg.head_dim)
        v = v.reshape(b, s, cfg.heads, cfg.head_dim)
        att = flash_attention(q, k, v, causal=False)
        att = att.reshape(b, s, cfg.hidden)
        x = x + att @ block["wo"]
        h = _layer_norm(x, block["norm2"], cfg.norm_eps)
        x = x + jax.nn.gelu(h @ block["w1"]) @ block["w2"]
    x = _layer_norm(x, params["norm_out"], cfg.norm_eps)
    pooled = x[:, 0] if cfg.pool == "cls" else x[:, 1:].mean(axis=1)
    return (pooled @ params["head_w"] + params["head_b"]).astype(jnp.float32)


def loss_fn(params, images: jax.Array, labels: jax.Array,
            cfg: ViTConfig) -> jax.Array:
    logits = forward(params, images, cfg)
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
