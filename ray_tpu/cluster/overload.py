"""Overload-robustness primitives: retry budgets and circuit breakers.

The fault plane (cluster/fault_plane.py) proves the cluster survives
drops, delays, and partitions; this module is the matching defense
against *load*. The failure shape it targets is the metastable retry
storm (Bronson et al., HotOS '21): a single stalled server turns N
healthy clients into an amplifying loop — every timeout or shed reply
becomes a retry, retries deepen the overload, and the system stays
wedged after the original trigger clears. The two client-side
mechanisms here, combined with server-side admission control in
cluster/rpc.py, bound that loop:

- :class:`RetryBudget` — a per-destination token bucket. Every retry
  spends one token; every success earns ``fraction`` tokens (capped).
  Aggregate retry traffic is therefore capped at roughly
  ``fraction x goodput`` plus a fixed initial burst — the SRE
  retry-budget discipline (reference: gRPC retry throttling's
  token_ratio, Google SRE book ch. 22).
- :class:`CircuitBreaker` — open after K consecutive failures, allow a
  single half-open probe after a cool-down, close on probe success.
  The open window honors the server's ``RetryLaterError`` backoff hint
  so an overloaded server's pushback sets the pace of re-contact.

Both are process-wide singletons PER DESTINATION (``budget_for`` /
``breaker_for``): every ``ResilientRpcClient`` in a process talking to
the same address shares one budget and one breaker, so the cap holds
for the process's aggregate traffic, not per client object. All state
transitions are deterministic (no randomness) — under a fault plan the
only jitter in the retry path remains the seeded backoff stream, so
overload scenarios replay from the plan's single seed.

Counters surface through observability.metrics (the Prometheus path)
and through :func:`snapshot` (the ``node_stats`` / ``cluster_view`` /
``cli.py status`` path).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ray_tpu.observability.metrics import (
    fastlane_breaker_transitions,
    rpc_breaker_transitions,
    rpc_retries_spent,
    rpc_retry_budget_exhausted,
)

_CLOSED, _OPEN, _HALF_OPEN = "closed", "open", "half_open"


class RetryBudget:
    """Token bucket capping retries at a fraction of goodput.

    The first attempt of a call is always free — the budget governs
    RETRIES only. ``try_spend`` takes one token (False = budget
    exhausted: give up and surface the error instead of amplifying);
    ``on_success`` earns ``fraction`` tokens up to ``cap``."""

    def __init__(self, fraction: float, initial: float, cap: float):
        self.fraction = float(fraction)
        self.cap = float(cap)
        self._tokens = min(float(initial), self.cap)
        self._lock = threading.Lock()
        self.num_spent = 0
        self.num_exhausted = 0

    @property
    def enabled(self) -> bool:
        return self.fraction > 0.0

    def try_spend(self) -> bool:
        if not self.enabled:
            return True  # budget disabled: never the limiting factor
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.num_spent += 1
                rpc_retries_spent.inc()
                return True
            self.num_exhausted += 1
            rpc_retry_budget_exhausted.inc()
            return False

    def on_success(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.fraction)

    def snapshot(self) -> dict:
        with self._lock:
            return {"tokens": round(self._tokens, 3),
                    "spent": self.num_spent,
                    "exhausted": self.num_exhausted}


class CircuitBreaker:
    """Per-destination breaker: closed -> open after ``threshold``
    consecutive failures; after ``reset_s`` (or the server's
    RetryLaterError hint, whichever is larger) one half-open probe is
    admitted; probe success closes, probe failure re-opens.

    ``allow()`` answers "may an attempt go to the wire right now";
    callers that cannot send report the remaining open window via
    ``remaining_s()`` and sleep it off instead of spinning."""

    def __init__(self, threshold: int, reset_s: float):
        self.threshold = int(threshold)
        self.reset_s = float(reset_s)
        self._lock = threading.Lock()
        self._state = _CLOSED
        self._failures = 0
        self._open_until = 0.0
        self._probe_inflight = False
        self.num_opens = 0

    @property
    def enabled(self) -> bool:
        return self.threshold > 0

    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        if not self.enabled:
            return True
        with self._lock:
            if self._state == _CLOSED:
                return True
            now = time.monotonic()
            if self._state == _OPEN and now >= self._open_until:
                self._state = _HALF_OPEN
                self._probe_inflight = False
            if self._state == _HALF_OPEN:
                if self._probe_inflight:
                    return False  # one probe at a time
                self._probe_inflight = True
                return True
            return False

    def remaining_s(self) -> float:
        """Seconds until the next probe is admitted (0 when closed or
        already probing)."""
        with self._lock:
            if self._state != _OPEN:
                return 0.0
            return max(0.0, self._open_until - time.monotonic())

    def record_success(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._state = _CLOSED
            self._failures = 0
            self._probe_inflight = False

    def record_failure(self, hint_s: Optional[float] = None) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._failures += 1
            window = max(self.reset_s, hint_s or 0.0)
            if self._state == _HALF_OPEN:
                # the probe failed: straight back to open
                self._open(window)
            elif self._state == _CLOSED \
                    and self._failures >= self.threshold:
                self._open(window)
            elif self._state == _OPEN:
                # a late failure (e.g. a hint-carrying shed from a
                # racing thread) extends the window to the newest hint
                self._open_until = max(
                    self._open_until, time.monotonic() + window)

    def _open(self, window: float) -> None:
        # caller holds the lock
        self._state = _OPEN
        self._open_until = time.monotonic() + window
        self._probe_inflight = False
        self.num_opens += 1
        rpc_breaker_transitions.inc(tags={"to": "open"})

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "consecutive_failures": self._failures,
                    "opens": self.num_opens}


# --------------------------------------------------------------------------
# process-wide per-destination registries
# --------------------------------------------------------------------------

_lock = threading.Lock()
_budgets: Dict[str, RetryBudget] = {}
_breakers: Dict[str, CircuitBreaker] = {}


def enabled() -> bool:
    """Master switch (Config.overload_enabled): off restores the
    pre-overload-plane behavior everywhere the plane is woven in."""
    from ray_tpu._private.config import Config

    return bool(Config.instance().overload_enabled)


def budget_for(address: str) -> RetryBudget:
    with _lock:
        b = _budgets.get(address)
        if b is None:
            from ray_tpu._private.config import Config

            cfg = Config.instance()
            b = RetryBudget(cfg.rpc_retry_budget_fraction,
                            cfg.rpc_retry_budget_initial,
                            cfg.rpc_retry_budget_cap)
            _budgets[address] = b
        return b


def breaker_for(address: str) -> CircuitBreaker:
    with _lock:
        br = _breakers.get(address)
        if br is None:
            from ray_tpu._private.config import Config

            cfg = Config.instance()
            br = CircuitBreaker(cfg.rpc_breaker_failure_threshold,
                                cfg.rpc_breaker_reset_s)
            _breakers[address] = br
        return br


_penalties: Dict[str, float] = {}  # destination -> monotonic expiry


def note_shed(address: str, hint_s: float) -> None:
    """Record a destination's RetryLaterError shed hint: callers that
    consult :func:`shed_penalty_remaining` weight the destination DOWN
    for ``hint_s`` (temporary exclusion while alternatives exist)
    instead of blindly retrying against a peer that just said "later".
    The serve router keys these by replica; the hint's pace is the
    overloaded peer's own pushback, exactly like the breaker's open
    window."""
    until = time.monotonic() + max(0.0, float(hint_s))
    with _lock:
        if until > _penalties.get(address, 0.0):
            _penalties[address] = until


def shed_penalty_remaining(address: str) -> float:
    """Seconds left on ``address``'s shed weight-down (0 = none)."""
    with _lock:
        until = _penalties.get(address)
        if until is None:
            return 0.0
        remaining = until - time.monotonic()
        if remaining <= 0.0:
            del _penalties[address]
            return 0.0
        return remaining


# --------------------------------------------------------------------------
# fast-lane degraded mode: per-LANE breakers over the master switches
# --------------------------------------------------------------------------

# The three rebuilt hot paths, keyed by the Config master switch each
# one hides behind. A lane breaker going open means "this lane keeps
# failing in lane-specific ways — run the safe pre-lane path until a
# half-open probe survives"; the master switch itself is never written,
# so operator intent (switch OFF) and degraded mode (switch ON, breaker
# open) stay distinguishable in the stats.
LANES = {
    "dispatch": "dispatch_fastlane_enabled",
    "data_plane": "data_plane_pipeline_enabled",
    "scheduler": "scheduler_pipeline_enabled",
}

_lane_breakers: Dict[str, CircuitBreaker] = {}


class _LaneBreaker(CircuitBreaker):
    """CircuitBreaker whose transitions are counted per lane on the
    fastlane counter (the rpc counter stays per-destination)."""

    def __init__(self, lane: str, threshold: int, reset_s: float):
        super().__init__(threshold, reset_s)
        self.lane = lane
        self._last_counted = _CLOSED

    def _open(self, window: float) -> None:
        super()._open(window)
        if self._last_counted != _OPEN:
            self._last_counted = _OPEN
            fastlane_breaker_transitions.inc(
                tags={"lane": self.lane, "to": "open"})

    def record_success(self) -> None:
        super().record_success()
        if self.enabled and self._last_counted == _OPEN:
            self._last_counted = _CLOSED
            fastlane_breaker_transitions.inc(
                tags={"lane": self.lane, "to": "closed"})


def lane_breaker(lane: str) -> CircuitBreaker:
    """The process-wide degraded-mode breaker for one fast lane."""
    if lane not in LANES:
        raise ValueError(f"unknown fast lane {lane!r}; "
                         f"choose from {sorted(LANES)}")
    with _lock:
        br = _lane_breakers.get(lane)
        if br is None:
            from ray_tpu._private.config import Config

            cfg = Config.instance()
            threshold = (cfg.fastlane_breaker_threshold
                         if cfg.fastlane_breaker_enabled else 0)
            br = _LaneBreaker(lane, threshold,
                              cfg.fastlane_breaker_reset_s)
            _lane_breakers[lane] = br
        return br


def lane_enabled(lane: str) -> bool:
    """Effective state of a fast lane's master switch: the Config
    switch AND'd with the lane breaker. Reads at the switch sites go
    through here; an ``allow()`` that returns True while the breaker is
    half-open IS the probe — the very next lane attempt reports back
    through :func:`lane_ok` / :func:`lane_failed`."""
    from ray_tpu._private.config import Config

    if not bool(getattr(Config.instance(), LANES[lane])):
        return False
    return lane_breaker(lane).allow()


def lane_ok(lane: str) -> None:
    """A lane-specific operation completed on the fast path."""
    lane_breaker(lane).record_success()


def lane_failed(lane: str) -> None:
    """A lane-specific failure (batch frame error, tree failover,
    fenced tick): K consecutive ones flip the lane to the safe path."""
    lane_breaker(lane).record_failure()


def snapshot() -> dict:
    """Per-destination budget/breaker states for the stats surfaces
    (node_stats -> heartbeat -> cluster_view -> `cli.py status`)."""
    with _lock:
        budgets = dict(_budgets)
        breakers = dict(_breakers)
        lanes = dict(_lane_breakers)
    return {
        "retry_budgets": {a: b.snapshot() for a, b in budgets.items()},
        "breakers": {a: br.snapshot() for a, br in breakers.items()},
        "lanes": {name: br.snapshot() for name, br in lanes.items()},
    }


def reset() -> None:
    """Forget every per-destination budget/breaker/penalty (tests)."""
    with _lock:
        _budgets.clear()
        _breakers.clear()
        _penalties.clear()
        _lane_breakers.clear()
