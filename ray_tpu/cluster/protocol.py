"""Wire protocol between the parent runtime and worker processes.

Frames: 8-byte big-endian length + body over the worker's stdin/stdout
pipes (the raylet<->worker control channel; the reference uses a unix
socket + gRPC, src/ray/core_worker/core_worker_process.cc).

Bodies are cloudpickle protocol-5 payloads. Out-of-band PickleBuffers
larger than ``SHM_THRESHOLD`` travel through the shared-memory store
(plasma equivalent) instead of the pipe: the body carries
``(pickled, inline_buffers, shm_ids)`` and the receiver stitches the
buffer list back together in order. Small messages stay fully inline so
the protocol works without the native store.
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import List, Optional, Tuple

import cloudpickle

SHM_THRESHOLD = 64 * 1024  # bytes; below this, inline in the frame
_LEN = struct.Struct(">Q")

# marker distinguishing inline from shm-carried buffers, in order
_INLINE = 0
_SHM = 1


class PipeClosedError(ConnectionError):
    """The peer process closed its end (it exited or was killed)."""


def write_frame(fp, body: bytes) -> None:
    fp.write(_LEN.pack(len(body)))
    fp.write(body)
    fp.flush()


def _read_exact(fp, n: int) -> bytes:
    """Pipes deliver short reads (raw unbuffered FileIO, 64KB pipe
    buffer): loop until the full n bytes arrive or the peer closes."""
    chunks = []
    remaining = n
    while remaining:
        chunk = fp.read(remaining)
        if not chunk:
            raise PipeClosedError(
                f"pipe closed with {remaining}/{n} bytes outstanding")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def read_frame(fp) -> bytes:
    (length,) = _LEN.unpack(_read_exact(fp, _LEN.size))
    return _read_exact(fp, length)


def dumps(obj, shm_store=None) -> bytes:
    """Serialize with protocol-5; large buffers spill to the shm store."""
    plan: List[Tuple[int, object]] = []  # (_INLINE, bytes) | (_SHM, oid)

    def _buffer_cb(pb: pickle.PickleBuffer):
        raw = pb.raw()
        if shm_store is not None and raw.nbytes >= SHM_THRESHOLD:
            oid = os.urandom(20)
            try:
                shm_store.put_bytes(oid, raw)
                plan.append((_SHM, oid))
                return False  # consumed out-of-band
            except Exception:
                pass  # store full/closed: fall through to inline
        plan.append((_INLINE, raw.tobytes()))
        return False

    pickled = cloudpickle.dumps(obj, protocol=5, buffer_callback=_buffer_cb)
    return pickle.dumps((pickled, plan), protocol=4)


def loads(body: bytes, shm_store=None):
    pickled, plan = pickle.loads(body)
    buffers = []
    shm_ids = []
    for kind, payload in plan:
        if kind == _INLINE:
            buffers.append(payload)
        else:
            if shm_store is None:
                raise RuntimeError(
                    "message carries shm buffers but no store is attached")
            data = shm_store.get_bytes(payload)
            if data is None:
                raise RuntimeError("shm buffer missing (evicted?)")
            buffers.append(data)
            shm_ids.append(payload)
    obj = pickle.loads(pickled, buffers=buffers)
    # The copies made by get_bytes are owned by `obj` now; drop the shm
    # entries so one-shot transfer buffers don't accumulate.
    for oid in shm_ids:
        try:
            shm_store.delete(oid)
        except Exception:
            pass
    return obj


def send(fp, obj, shm_store=None) -> None:
    write_frame(fp, dumps(obj, shm_store))


def recv(fp, shm_store=None):
    return loads(read_frame(fp), shm_store)


def format_exception(exc: BaseException) -> tuple:
    """(pickled exception | None, traceback text, repr) — the exception
    object itself may not be picklable; the parent falls back to repr."""
    import traceback

    tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
    try:
        payload: Optional[bytes] = cloudpickle.dumps(exc)
        pickle.loads(payload)  # must round-trip parent-side too
    except Exception:
        payload = None
    return payload, tb, repr(exc)


def restore_exception(payload, tb: str, rep: str) -> BaseException:
    if payload is not None:
        try:
            exc = pickle.loads(payload)
            exc._worker_traceback = tb
            return exc
        except Exception:
            pass
    return RuntimeError(f"task failed in worker process: {rep}\n{tb}")
