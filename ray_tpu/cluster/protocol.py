"""Wire protocol between the parent runtime and worker processes.

Frames: 8-byte big-endian length + body over the worker's stdin/stdout
pipes (the raylet<->worker control channel; the reference uses a unix
socket + gRPC, src/ray/core_worker/core_worker_process.cc).

Bodies are cloudpickle protocol-5 payloads. Out-of-band PickleBuffers
larger than ``SHM_THRESHOLD`` travel through the shared-memory store
(plasma equivalent) instead of the pipe: the body carries
``(pickled, inline_buffers, shm_ids)`` and the receiver stitches the
buffer list back together in order. Small messages stay fully inline so
the protocol works without the native store.
"""

from __future__ import annotations

import logging
import os
import pickle
import struct
from typing import List, Optional, Tuple

import cloudpickle

logger = logging.getLogger(__name__)

SHM_THRESHOLD = 64 * 1024  # bytes; below this, inline in the frame
_LEN = struct.Struct(">Q")

# Pipe-protocol version: the parent passes it on the worker command
# line and the worker refuses a mismatch at startup (parent and child
# normally come from one checkout, but a worker resolved against a
# stale install must fail loudly, not mis-parse frames). Bump on any
# incompatible change to the frame or marker-class layout.
# History: 1 = framed cloudpickle-5 + shm out-of-band buffers +
#              StoredObjectArg/StoredResult/FlatPayload markers.
PIPE_PROTOCOL_VERSION = 1

# marker distinguishing inline from shm-carried buffers, in order
_INLINE = 0
_SHM = 1


class PipeClosedError(ConnectionError):
    """The peer process closed its end (it exited or was killed)."""


class StoredObjectArg:
    """Marker for a task argument whose payload sits in a shm store
    segment ON THIS HOST: the raylet sends this 20-byte key down the
    pipe instead of the (possibly huge) value, and the worker reads the
    segment directly — the plasma worker-mmap contract (reference:
    workers map plasma and deserialize in place; only metadata crosses
    the socket). ``path`` is None for the node's own segment, or a
    same-host PEER raylet's segment — consuming a neighbour's object
    costs a pin and a page-table walk, not a copy (plasma's one-store-
    per-host model). The raylet holds a pin until the task ends."""

    __slots__ = ("key", "path", "offset", "size")

    def __init__(self, key: bytes, path: Optional[str] = None,
                 offset: Optional[int] = None,
                 size: Optional[int] = None):
        self.key = key
        self.path = path
        # peer-segment args carry the pinned block's (offset, size): the
        # worker reads the region under the raylet's pin without a
        # state lookup, so a concurrent spill/delete on the OWNER (which
        # defers while pinned) cannot fail the read
        self.offset = offset
        self.size = size


class StoredResult:
    """Marker reply for a task result the worker wrote directly into
    the node's shm store segment under the return key (plasma: workers
    create+seal in the store; the raylet merely pins). Carries the
    payload size for the raylet's capacity accounting."""

    __slots__ = ("nbytes",)

    def __init__(self, nbytes: int):
        self.nbytes = nbytes


class FlatPayload:
    """Reply wrapper for a small task result already serialized in the
    flat stored-object format: the raylet stores ``body`` verbatim
    instead of deserializing the value and re-serializing it — one
    serialization per result, total."""

    __slots__ = ("body",)

    def __init__(self, body: bytes):
        self.body = body


def write_frame(fp, body: bytes) -> None:
    fp.write(_LEN.pack(len(body)))
    fp.write(body)
    fp.flush()


def _read_exact(fp, n: int) -> bytes:
    """Pipes deliver short reads (raw unbuffered FileIO, 64KB pipe
    buffer): loop until the full n bytes arrive or the peer closes."""
    chunks = []
    remaining = n
    while remaining:
        chunk = fp.read(remaining)
        if not chunk:
            raise PipeClosedError(
                f"pipe closed with {remaining}/{n} bytes outstanding")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def read_frame(fp) -> bytes:
    (length,) = _LEN.unpack(_read_exact(fp, _LEN.size))
    return _read_exact(fp, length)


def dumps(obj, shm_store=None) -> bytes:
    """Serialize with protocol-5; large buffers spill to the shm store."""
    plan: List[Tuple[int, object]] = []  # (_INLINE, bytes) | (_SHM, oid)

    def _buffer_cb(pb: pickle.PickleBuffer):
        raw = pb.raw()
        if shm_store is not None and raw.nbytes >= SHM_THRESHOLD:
            oid = os.urandom(20)
            try:
                shm_store.put_bytes(oid, raw)
                plan.append((_SHM, oid))
                return False  # consumed out-of-band
            except Exception as e:
                # store full/closed: fall through to inline
                logger.debug("shm spill of %d-byte buffer failed; "
                             "inlining: %r", raw.nbytes, e)
        plan.append((_INLINE, raw.tobytes()))
        return False

    pickled = cloudpickle.dumps(obj, protocol=5, buffer_callback=_buffer_cb)
    return pickle.dumps((pickled, plan), protocol=4)


def loads(body: bytes, shm_store=None):
    pickled, plan = pickle.loads(body)
    buffers = []
    shm_ids = []
    for kind, payload in plan:
        if kind == _INLINE:
            buffers.append(payload)
        else:
            if shm_store is None:
                raise RuntimeError(
                    "message carries shm buffers but no store is attached")
            data = shm_store.get_bytes(payload)
            if data is None:
                raise RuntimeError("shm buffer missing (evicted?)")
            buffers.append(data)
            shm_ids.append(payload)
    obj = pickle.loads(pickled, buffers=buffers)
    # The copies made by get_bytes are owned by `obj` now; drop the shm
    # entries so one-shot transfer buffers don't accumulate.
    for oid in shm_ids:
        try:
            shm_store.delete(oid)
        except Exception as e:
            # leaked one-shot buffer; segment close reclaims it
            logger.debug("transfer-buffer %s cleanup failed: %r",
                         oid.hex()[:8], e)
    return obj


# --------------------------------------------------------------------------
# Flat STORED-OBJECT format (the object-store payload layout).
#
# Layout: 8-byte header length | header | buffer0 | buffer1 | ...
# where header = pickle((pickled_obj, [buffer sizes])). The point of the
# flatness: `loads_flat` reconstructs pickle-5 out-of-band buffers as
# SLICES OF THE INPUT VIEW — deserializing straight out of a pinned shm
# segment costs zero copies and faults only the pages actually touched
# (the plasma zero-copy read contract: workers mmap the store and numpy
# arrays view it in place). Views are handed out READ-ONLY, matching the
# reference's immutable-object semantics for plasma-backed arrays.
# --------------------------------------------------------------------------

def flat_parts(obj) -> Tuple[bytes, List]:
    """(header, raw_buffers) for writing an object in the flat format."""
    bufs: List = []

    def _cb(pb: pickle.PickleBuffer):
        raw = pb.raw()
        bufs.append(raw if raw.ndim == 1 else raw.cast("B"))
        return False

    pickled = cloudpickle.dumps(obj, protocol=5, buffer_callback=_cb)
    header = pickle.dumps((pickled, [b.nbytes for b in bufs]), protocol=5)
    return header, bufs


def flat_size(header: bytes, bufs: List) -> int:
    return _LEN.size + len(header) + sum(b.nbytes for b in bufs)


def write_flat(dest, header: bytes, bufs: List) -> None:
    """Assemble the flat layout into ``dest`` (a writable buffer of
    exactly flat_size bytes) — used to serialize DIRECTLY into a shm
    segment allocation with no intermediate joined copy."""
    mv = memoryview(dest)
    mv[:_LEN.size] = _LEN.pack(len(header))
    off = _LEN.size
    mv[off:off + len(header)] = header
    off += len(header)
    for b in bufs:
        n = b.nbytes
        mv[off:off + n] = b
        off += n


def dumps_flat(obj) -> bytearray:
    header, bufs = flat_parts(obj)
    out = bytearray(flat_size(header, bufs))
    write_flat(out, header, bufs)
    return out


def loads_flat(body):
    """Deserialize a flat payload. ``body`` may be bytes or a memoryview
    over a pinned shm segment — big buffers become read-only views of
    it, so the caller must keep the underlying pin/owner alive for the
    lifetime of the returned object's arrays."""
    view = memoryview(body).toreadonly()
    if len(view) and view[0] == 0x80:
        # legacy inline-pickle payload (0x80 = pickle PROTO opcode; a
        # flat header-length big-endian u64 always starts 0x00)
        return loads(bytes(view))
    (hlen,) = _LEN.unpack(view[:_LEN.size])
    pickled, sizes = pickle.loads(view[_LEN.size:_LEN.size + hlen])
    off = _LEN.size + hlen
    buffers = []
    for n in sizes:
        buffers.append(view[off:off + n])
        off += n
    return pickle.loads(pickled, buffers=buffers)


def send(fp, obj, shm_store=None) -> None:
    write_frame(fp, dumps(obj, shm_store))


def recv(fp, shm_store=None):
    return loads(read_frame(fp), shm_store)


def format_exception(exc: BaseException) -> tuple:
    """(pickled exception | None, traceback text, repr) — the exception
    object itself may not be picklable; the parent falls back to repr."""
    import traceback

    tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
    try:
        payload: Optional[bytes] = cloudpickle.dumps(exc)
        pickle.loads(payload)  # must round-trip parent-side too
    except Exception:
        payload = None
    return payload, tb, repr(exc)


def restore_exception(payload, tb: str, rep: str) -> BaseException:
    if payload is not None:
        try:
            exc = pickle.loads(payload)
            exc._worker_traceback = tb
            return exc
        except Exception as e:
            # fall through to the repr-based RuntimeError below
            logger.debug("stored exception payload failed to "
                         "unpickle: %r", e)
    return RuntimeError(f"task failed in worker process: {rep}\n{tb}")
