"""Tiered node-local object store for the process tier — the plasma
equivalent, with the properties the reference store has and a flat dict
does not (reference: src/ray/object_manager/plasma/{object_lifecycle_
manager.h, eviction_policy.h:160, create_request_queue.cc} and
src/ray/raylet/local_object_manager.h:37,89):

- **Three storage tiers.** Small objects live in the Python heap;
  objects >= ``shm_min_bytes`` live in the node's native shared-memory
  segment (``_native/shm_store.cpp``) so same-host peers and workers can
  read them without a TCP hop; spilled objects live as files under the
  spill directory.
- **Capacity is enforced on put** (the round-3 verdict's top object-plane
  gap: `ByteStore.put` appended unconditionally). When a put would
  exceed capacity the store reclaims, cheapest first: LRU *replica*
  copies are dropped outright (they exist on another node — the
  equivalent of plasma's LRU eviction of unpinned objects), then LRU
  *primary* copies are spilled to disk (local_object_manager.h:89
  SpillObjects). An object bigger than the whole store falls back
  directly to disk (plasma's fallback allocation).
- **Create backpressure.** Reclamation happens synchronously inside the
  putting call, so a producer that outruns the store pays the spill IO
  itself — the process-tier analogue of plasma's create-request queue,
  which parks creates until space exists (create_request_queue.cc).
- **Transparent restore.** A get/serve of a spilled object reads it back
  from disk (and re-admits it through the same capacity gate).
- **Replica-drop notification.** Dropping a replica invalidates its GCS
  location entry; the store queues the id and a background flusher
  deregisters it, so eviction never blocks on a GCS round trip.

Shm entries are kept *pinned* (refcount >= 1) for their in-memory
lifetime so the C store's own LRU eviction can never silently drop a
primary copy out from under the Python-level accounting; eviction and
spill decisions all happen here, where primariness is known.
"""

from __future__ import annotations

import hashlib
import logging
import os
import re
import shutil
import tempfile
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.cluster import integrity
from ray_tpu.exceptions import ObjectCorruptedError

logger = logging.getLogger(__name__)

_MEM, _SHM, _DISK = "mem", "shm", "disk"


_attach_lock = threading.Lock()
_attach_cache: Dict[str, object] = {}


def attach_shm(path: str):
    """Attach (and cache, process-wide) a peer's shm segment for
    same-host reads. Returns None when the segment is unreachable —
    the path not existing is the same-host test itself (/dev/shm files
    are host-local). Readers copy under the C store's process-shared
    mutex, so a concurrent delete by the owner cannot tear the read."""
    with _attach_lock:
        seg = _attach_cache.get(path)
        if seg is not None:
            return seg
        if not os.path.exists(path):
            return None
        try:
            from ray_tpu._native.shm_store import ShmStore

            seg = ShmStore.open(path)
        except Exception:
            return None
        _attach_cache[path] = seg
        return seg


def sweep_stale_segments(min_age_s: Optional[float] = None) -> int:
    """Unlink shm segments (and spill dirs) whose creating process is
    dead. Segment files are named ``ray_tpu_store_<pid>_<token>``; a
    SIGKILLed raylet (chaos tests kill nodes by design, and the OOM
    killer is real) never reaches its unlink, and the leaked tmpfs
    pages are RESIDENT RAM — on the r05 build box 279 leaked segments
    held 125 GiB and starved the host to 270 MB available, OOM-killing
    later raylets at boot. Plasma's analogue is its stale-session
    sweep. Unlinking while a live consumer still maps the file is safe
    (the mapping persists until munmap). Returns the number removed.

    Only entries whose mtime is older than ``min_age_s`` (default:
    Config.byte_store_sweep_min_age_s, a few minutes) are removed: the
    dead-pid check alone is not sufficient proof of staleness — a
    legacy pid-less spill dir (``ray_tpu_spill_<rand8>``) can parse an
    all-digit random suffix as a pid, and a recycled pid maps a LIVE
    process onto a dead owner's name — in either miss the victim is a
    running process's spill data. Age covers both: an actively-used
    spill dir keeps a fresh mtime (entries are created/removed in it),
    and a just-booted recycled-pid store is younger than the threshold,
    while a genuinely leaked segment only ever gets older."""
    if min_age_s is None:
        from ray_tpu._private.config import Config

        min_age_s = Config.instance().byte_store_sweep_min_age_s
    # age is measured against filesystem st_mtime values, which are
    # wall-clock by definition
    now = time.time()  # raycheck: disable=RC02
    removed = 0
    # anchored patterns: segment files are ray_tpu_store_<pid>_<token>,
    # spill dirs ray_tpu_spill_<pid> (ByteStore) or
    # ray_tpu_spill_<pid>_<rand> (in-process mkdtemp). An unanchored
    # match could misparse a pid-less random suffix as a pid and rmtree
    # a LIVE store's spilled objects (r05 review finding)
    for base, pat in (
            ("/dev/shm", re.compile(r"^ray_tpu_store_(\d+)_")),
            (tempfile.gettempdir(),
             re.compile(r"^ray_tpu_(?:store|spill)_(\d+)(?:_|$)"))):
        try:
            names = os.listdir(base)
        except OSError:
            continue
        for name in names:
            m = pat.match(name)
            if not m:
                continue
            pid = int(m.group(1))
            try:
                os.kill(pid, 0)
                continue  # owner alive
            except ProcessLookupError as e:
                # owner is gone: this entry is a sweep candidate
                logger.debug("sweep: owner pid %d of %s is dead: %r",
                             pid, name, e)
            except PermissionError:
                continue  # alive, other user
            except (OverflowError, OSError):
                # a pid-like number too large for the C long (stray
                # file): skip the entry, never abort the whole sweep —
                # a dead sweep silently reintroduces the leak
                continue
            path = os.path.join(base, name)
            try:
                if now - os.stat(path).st_mtime < min_age_s:
                    continue  # too young to be provably stale
            except OSError:
                continue  # vanished under us (concurrent sweep)
            try:
                if os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)
                else:
                    os.unlink(path)
                removed += 1
            except OSError as e:
                # permissions or a concurrent sweep won the unlink
                logger.debug("sweep: removing %s failed: %r", path, e)
    return removed


def shm_key(object_id: bytes) -> bytes:
    """20-byte shm-store key for an arbitrary-length object id.
    Hashed (not truncated): structured ids — e.g. ObjectID's
    task-id-prefix layout (_private/ids.py) — share long prefixes, and
    truncation would collide every return of one task."""
    return hashlib.blake2b(object_id, digest_size=20).digest()


class _Entry:
    __slots__ = ("is_error", "where", "buf", "size", "primary", "path",
                 "pins", "crc", "seg", "seg_path")

    def __init__(self, is_error: bool, where: str, buf, size: int,
                 primary: bool, path: Optional[str] = None,
                 crc: Optional[int] = None, seg=None,
                 seg_path: Optional[str] = None):
        self.is_error = is_error
        self.where = where
        self.buf = buf          # bytes (mem) | pinned memoryview (shm)
        self.size = size
        self.primary = primary
        self.path = path        # spill file (disk)
        # integrity plane: crc32 computed once at creation; rides every
        # transfer of this object and is verified at each seam
        self.crc = crc
        # data-plane adoption: for a same-host replica that is a shared
        # MAPPING of a peer's sealed segment entry (not a copy), the
        # attached peer segment holding our refcount pin, and its path
        # (so offers/zero-copy reads of this object point readers at
        # the segment that actually holds the bytes). None = the entry
        # lives in this store's own segment/heap.
        self.seg = seg
        self.seg_path = seg_path
        # pin count: >0 means some task is using this object as an
        # argument right now — reclaim must not evict or spill it
        # (reference: DependencyManager pins task args; plasma pins via
        # client refcount, object_lifecycle_manager.h)
        self.pins = 0


class ReceiveHandle:
    """An in-progress streamed receive: the object's final segment
    bytes, preallocated at ``push_begin`` time so every chunk is copied
    ONCE — from the socket straight to its final shm offset via
    ``recv_into`` on a slice of :attr:`view` (readinto the preallocated
    segment; the reference ObjectManager's chunked receive, minus its
    intermediate chunk buffers). Not an entry yet: invisible to
    lookups until :meth:`ByteStore.seal_receive` admits it."""

    __slots__ = ("object_id", "size", "is_error", "crc", "view", "shm",
                 "_buf", "_trailer", "landed", "crc_state", "t0",
                 "t_last")

    def __init__(self, object_id: bytes, size: int, is_error: bool,
                 crc: Optional[int]):
        self.object_id = object_id
        self.size = size
        self.is_error = is_error
        self.crc = crc          # sender's whole-object digest (begin)
        self.view = None        # writable payload view (chunks land here)
        self.shm = False
        self._buf = None        # full allocation incl. trailer space
        self._trailer = 0
        self.landed = 0         # coverage: bytes landed so far
        self.crc_state = 0      # running fused digest of landed bytes
        self.t0 = time.monotonic()
        self.t_last = self.t0   # staleness: last progress timestamp


class ByteStore:
    """Node-local object store holding sealed, immutable pickled
    payloads, LRU-ordered. Thread-safe. See module docstring."""

    def __init__(self, capacity: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 shm_min_bytes: int = 64 * 1024,
                 use_shm: bool = True,
                 on_replica_dropped: Optional[Callable[[bytes], None]] = None):
        from ray_tpu._private.config import Config

        cfg = Config.instance()
        # every store boot reclaims segments orphaned by SIGKILLed
        # owners first — their tmpfs pages are resident RAM and a few
        # leaked GiB-scale segments can OOM this very boot's prefault
        try:
            n = sweep_stale_segments()
            if n:
                logger.info("swept %d stale shm segments/spill dirs", n)
        except Exception as e:  # the sweep must never block a boot
            logger.debug("stale-segment sweep at boot failed: %r", e)
        self.capacity = capacity or cfg.object_store_memory
        self.shm_min_bytes = shm_min_bytes
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._entries: "OrderedDict[bytes, _Entry]" = OrderedDict()
        # deleted-while-pinned entries: invisible to lookups, bytes kept
        # until the last unpin (plasma delete-while-in-use semantics)
        self._condemned: Dict[bytes, _Entry] = {}
        self.total_bytes = 0        # mem + shm tiers (disk doesn't count)
        self.num_spilled = 0
        self.num_replicas_dropped = 0
        self.num_restored = 0
        self._on_replica_dropped = on_replica_dropped
        self._spill_dir = spill_dir or (
            cfg.spill_directory
            or os.path.join(tempfile.gettempdir(),
                            f"ray_tpu_spill_{os.getpid()}"))
        self._shm = None
        self.shm_path: Optional[str] = None
        if use_shm:
            try:
                from ray_tpu._native.shm_store import ShmStore

                # headroom beyond `capacity`: the C store's entry table
                # + allocator rounding, plus room for TRANSIENT transfer
                # buffers (worker<->raylet out-of-band pickle-5 buffers
                # and in-flight worker result writes live in the same
                # segment but outside this store's accounting)
                headroom = max(64 * 1024 * 1024, self.capacity // 4)
                self._shm = ShmStore(capacity=self.capacity + headroom
                                     + 16 * 1024 * 1024)
                self.shm_path = self._shm.path
            except Exception as e:  # native unavailable: mem-only
                logger.info("shm store unavailable (%s); "
                            "using heap tier only", e)
        # integrity plane: corrupt replicas discarded at a verify seam
        # and orphan spill files re-adopted (or dropped) at boot
        self.num_corrupt_dropped = 0
        self.num_orphans_adopted = 0
        # data-plane pipeline: in-progress streamed receives (chunks
        # landing straight in their final segment bytes) and same-host
        # segment adoptions (replica = shared mapping, zero bytes moved)
        self._receiving: Dict[bytes, ReceiveHandle] = {}
        self.num_shm_adopts = 0
        self.num_rx_aborted = 0
        # boot-time orphan-spill reclaim: only when the spill dir is
        # EXPLICIT (ctor arg or Config.spill_directory) — sharing a
        # directory across incarnations is then intentional, and a
        # restarted raylet re-serves what its predecessor spilled
        # instead of stranding it. The default pid-derived dir is
        # always fresh, so adoption there would only cross-talk
        # same-process stores in tests.
        if spill_dir or cfg.spill_directory:
            try:
                self._adopt_orphan_spills()
            except Exception as e:  # adoption must never block a boot
                logger.warning("orphan spill reclaim failed: %r", e)
        from ray_tpu.scheduler.pull_manager import PullManager

        self.pull_manager = PullManager(self.capacity)

    def _adopt_orphan_spills(self) -> None:
        """Re-adopt spill files a previous incarnation left in the
        (explicit) spill dir — verifying each file's header digest
        first and DROPPING corrupt ones (counted) instead of re-serving
        bytes a dying raylet half-wrote. Files are named by object-id
        hex, so the id is recoverable; ``.tmp`` leftovers of torn
        ``os.replace`` writes are removed outright."""
        try:
            names = os.listdir(self._spill_dir)
        except OSError:
            return
        for name in sorted(names):
            path = os.path.join(self._spill_dir, name)
            if name.endswith(".tmp"):
                try:
                    os.unlink(path)
                except OSError as e:
                    logger.debug("removing torn spill tmp %s failed: "
                                 "%r", name, e)
                continue
            try:
                object_id = bytes.fromhex(name)
            except ValueError:
                continue  # not a spill file of ours
            try:
                with open(path, "rb") as f:
                    raw = f.read()
                is_error, payload, crc = integrity.parse_spill(raw)
                if crc is not None and integrity.enabled():
                    integrity.verify(payload, crc, "orphan_reclaim",
                                     object_id)
                elif crc is None:
                    # headerless-crc file (written with the plane off):
                    # unverifiable — adopting it would re-serve bytes
                    # nobody can vouch for
                    raise ValueError("spill file carries no digest")
            except ObjectCorruptedError:
                self.num_corrupt_dropped += 1
                try:
                    os.unlink(path)
                except OSError as e:
                    logger.debug("unlinking corrupt orphan spill %s "
                                 "failed: %r", name[:16], e)
                logger.warning("orphan spill %s failed its digest; "
                               "dropped", name[:16])
                continue
            except (OSError, ValueError) as e:
                # torn header / unreadable file: same treatment as a
                # failed digest — drop, never re-serve
                integrity.record_corruption("orphan_reclaim")
                self.num_corrupt_dropped += 1
                try:
                    os.unlink(path)
                except OSError as err:
                    logger.debug("unlinking unreadable orphan spill "
                                 "%s failed: %r", name[:16], err)
                logger.warning("orphan spill %s unreadable (%r); "
                               "dropped", name[:16], e)
                continue
            with self._cv:
                if object_id in self._entries:
                    continue
                self._entries[object_id] = _Entry(
                    is_error, _DISK, None, len(payload), True, path,
                    crc=crc)
                self.num_orphans_adopted += 1
                self._cv.notify_all()

    # ------------------------------------------------------------- queries
    def entries(self) -> List[Tuple[bytes, int]]:
        """(object_id, size) of every resident object (all tiers — a
        spilled object is still restorable here), for the re-report
        after a GCS restart wipes the location directory."""
        with self._lock:
            return [(oid, e.size) for oid, e in self._entries.items()]

    def contains(self, object_id: bytes) -> bool:
        with self._lock:
            return object_id in self._entries

    def info(self, object_id: bytes) -> Optional[dict]:
        """Tier/size metadata for transfer negotiation, or None."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return None
            return {"size": e.size, "is_error": e.is_error,
                    "where": e.where, "crc": e.crc,
                    "shm_path": self._shm_path_of(e)}

    def stats(self) -> dict:
        from ray_tpu.observability.metrics import object_store_bytes

        with self._lock:
            object_store_bytes.set(self.total_bytes)
            by_tier: Dict[str, int] = {_MEM: 0, _SHM: 0, _DISK: 0}
            for e in self._entries.values():
                by_tier[e.where] += 1
            return {"num_objects": len(self._entries),
                    "total_bytes": self.total_bytes,
                    "capacity": self.capacity,
                    "tiers": by_tier,
                    "num_spilled": self.num_spilled,
                    "num_restored": self.num_restored,
                    "num_replicas_dropped": self.num_replicas_dropped,
                    "num_corrupt_dropped": self.num_corrupt_dropped,
                    "num_orphans_adopted": self.num_orphans_adopted,
                    "num_shm_adopts": self.num_shm_adopts,
                    "num_rx_aborted": self.num_rx_aborted,
                    "num_receiving": len(self._receiving),
                    "shm": self._shm.stats() if self._shm else None}

    # ----------------------------------------------------------------- put
    def put(self, object_id: bytes, payload, is_error: bool = False,
            primary: bool = True, crc: Optional[int] = None) -> bool:
        """Store a sealed payload. Returns False if already present.
        ``primary=False`` marks a replica pulled from a peer — the
        cheapest thing to evict under pressure. ``crc`` is the
        integrity digest a verified transfer seam already holds; when
        omitted it is computed once, at creation, INSIDE the admit —
        fused with the tier copy so the digest reads bytes the memcpy
        just made cache-hot instead of a second cold traversal (the
        integrity plane's compute-once contract, ROADMAP 3a)."""
        size = len(payload)
        with self._cv:
            if object_id in self._entries:
                return False
            if size > self.capacity:
                # fallback allocation: bigger than the whole store goes
                # straight to disk (plasma_allocator.cc fallback mmap)
                entry = self._spill_payload(object_id, payload, is_error,
                                            primary, crc)
            else:
                self._reclaim_locked(size)
                entry = self._admit_locked(object_id, payload, is_error,
                                           primary, crc)
            self._entries[object_id] = entry
            self._cv.notify_all()
        return True

    def _admit_locked(self, object_id: bytes, payload, is_error: bool,
                      primary: bool, crc: Optional[int] = None) -> _Entry:
        size = len(payload)
        # digest-once, fused with the admit copy: a caller-supplied crc
        # (a verified transfer seam's) is adopted verbatim; otherwise it
        # is computed below on the bytes the tier copy just touched, so
        # payload is traversed once through cache instead of one cold
        # digest pass plus one cold copy pass
        want_crc = crc is None and integrity.enabled()
        if self._shm is not None and size >= self.shm_min_bytes:
            try:
                key = shm_key(object_id)
                # integrity trailer: the segment entry carries
                # payload + magic + crc, so ANY same-host reader
                # (peer raylet, driver) can verify the bytes it copies;
                # the logical size excludes the trailer
                trailer_len = (integrity.TRAILER_SIZE
                               if crc is not None or want_crc else 0)
                buf = self._shm.create(key, size + trailer_len)
                buf[:size] = payload
                if want_crc:
                    crc = integrity.checksum(
                        payload if type(payload) is bytes else buf[:size])
                if trailer_len:
                    buf[size:] = integrity.pack_trailer(crc)
                self._shm.seal(key)
                pinned = self._shm.get_buffer(key)  # refcount 1: the C
                # store's own LRU can never evict it behind our back
                self.total_bytes += size
                return _Entry(is_error, _SHM, pinned[:size], size,
                              primary, crc=crc)
            except (MemoryError, KeyError, OSError) as e:
                # fragmentation or segment oddity: heap fallback
                logger.debug("shm admit of %s (%d bytes) fell back to "
                             "heap: %r", object_id.hex()[:8], size, e)
        data = bytes(payload)  # no-op when payload is already bytes
        if want_crc:
            crc = integrity.checksum(data)
        self.total_bytes += size
        return _Entry(is_error, _MEM, data, size, primary, crc=crc)

    def _reclaim_locked(self, want: int) -> None:
        """Free memory until ``want`` more bytes fit under capacity:
        drop LRU replicas first, then spill LRU primaries. Pinned
        entries are untouchable — when everything is pinned, the put
        proceeds over capacity (a bounded transient: pins are held only
        for the duration of one task's argument use, and plasma makes
        the same over-commit choice with its fallback allocations
        rather than deadlocking the create queue)."""
        if self.total_bytes + want <= self.capacity:
            return
        # pass 1: replicas (another node has the primary; re-pullable)
        for oid in [o for o, e in self._entries.items()
                    if not e.primary and e.where != _DISK
                    and e.pins == 0]:
            if self.total_bytes + want <= self.capacity:
                return
            self._drop_tier_locked(oid)
            del self._entries[oid]
            self.num_replicas_dropped += 1
            if self._on_replica_dropped is not None:
                self._on_replica_dropped(oid)
        # pass 2: spill primaries, LRU first
        for oid in [o for o, e in self._entries.items()
                    if e.where != _DISK and e.pins == 0]:
            if self.total_bytes + want <= self.capacity:
                return
            e = self._entries[oid]
            payload = self._payload_locked(e)
            self._drop_tier_locked(oid)
            self._entries[oid] = self._spill_payload(
                oid, payload, e.is_error, e.primary, e.crc)

    def _spill_payload(self, object_id: bytes, payload, is_error: bool,
                       primary: bool, crc: Optional[int] = None) -> _Entry:
        os.makedirs(self._spill_dir, exist_ok=True)
        path = os.path.join(self._spill_dir, object_id.hex())
        tmp = path + ".tmp"
        if crc is None and integrity.enabled():
            crc = integrity.checksum(payload)
        # seeded fault hook: the `corrupt` rule kind flips a byte of the
        # bytes WRITTEN (the header digest reflects the true payload),
        # modeling at-rest spill corruption deterministically
        from ray_tpu.cluster import fault_plane as _fault

        plane = _fault.get_plane()
        if plane is not None:
            fault = plane.decide("spill", "byte_store", object_id.hex())
            if fault is not None and fault["action"] == "corrupt":
                payload = _fault.apply_corruption(payload, fault)
        with open(tmp, "wb") as f:
            f.write(integrity.pack_spill_header(is_error, crc))
            f.write(payload)
        os.replace(tmp, path)
        self.num_spilled += 1
        return _Entry(is_error, _DISK, None, len(payload), primary, path,
                      crc=crc)

    def _drop_tier_locked(self, object_id: bytes,
                          entry: Optional[_Entry] = None) -> None:
        """Release the in-memory bytes of an entry (mem or shm tier)."""
        e = entry if entry is not None else self._entries[object_id]
        if e.where == _SHM:
            key = shm_key(object_id)
            try:
                e.buf.release()  # the memoryview slice
            except AttributeError as err:
                # defensive: a shm entry's buf is always a memoryview
                logger.debug("entry %s buffer lacks release(): %r",
                             object_id.hex()[:8], err)
            if e.seg is not None:
                # adopted mapping of a peer's segment: drop OUR pin only
                # — the owner (whose deferred delete our refcount holds
                # open) garbage-collects the block; deleting a foreign
                # key is not ours to do
                try:
                    e.seg.release(key)
                except Exception as err:
                    logger.debug("releasing adopted mapping of %s "
                                 "failed: %r", object_id.hex()[:8], err)
            else:
                self._shm.release(key)
                self._shm.delete(key)
        if e.where in (_MEM, _SHM) and e.seg is None:
            # adopted entries never counted: their bytes live in the
            # OWNER's segment (one physical copy per host)
            self.total_bytes -= e.size
        e.buf = None

    def _read_spill_fused(self, e: _Entry, object_id: bytes) -> bytes:
        """Restore a spill file with its digest FUSED into the read:
        each ``readinto`` slice is folded into the running crc while
        still cache-hot (``integrity.checksum_update``), so a restore
        costs one pass through the payload instead of a read pass plus
        a cold verify pass — the PR 11 put-side fusion, applied to the
        spill-restore seam. Raises ObjectCorruptedError on mismatch
        (counted by the caller), ValueError on a torn layout."""
        with open(e.path, "rb") as f:
            head = f.read(integrity.SPILL_HEADER_SIZE)
            _, _, crc = integrity.parse_spill(head)
            buf = bytearray(e.size)
            mv = memoryview(buf)
            state, off = 0, 0
            check = crc is not None and integrity.enabled()
            while off < e.size:
                n = f.readinto(mv[off:off + (4 << 20)])
                if not n:
                    raise ValueError(
                        f"spill file truncated at {off}/{e.size}")
                if check:
                    state = integrity.checksum_update(
                        state, mv[off:off + n])
                off += n
            if f.read(1):
                raise ValueError("spill file longer than its header "
                                 "claims")
        if check and state != crc:
            integrity.record_corruption("spill_restore")
            raise ObjectCorruptedError(
                object_id.hex(), "spill_restore",
                f"object {object_id.hex()[:16]} failed checksum "
                f"verification at seam 'spill_restore' "
                f"(expected {crc:#010x}, got {state:#010x}); "
                f"corrupt replica discarded")
        return bytes(buf)

    def _payload_locked(self, e: _Entry):
        if e.where == _DISK:
            with open(e.path, "rb") as f:
                raw = f.read()
            _, payload, _ = integrity.parse_spill(raw)
            return bytes(payload)
        if e.where == _SHM:
            return bytes(e.buf)
        return e.buf

    # ----------------------------------------------------------------- get
    def get(self, object_id: bytes) -> Optional[Tuple[bool, bytes]]:
        """Returns (is_error, payload) or None. A spilled object is
        restored from disk (and re-admitted through the capacity gate,
        so a restore can itself spill something colder). A restore
        whose bytes fail the spill header's digest raises
        :class:`~ray_tpu.exceptions.ObjectCorruptedError` and DISCARDS
        the replica — the caller re-pulls from another holder or falls
        through to lineage reconstruction instead of serving garbage."""
        with self._cv:
            e = self._entries.get(object_id)
            if e is None:
                return None
            self._entries.move_to_end(object_id)  # LRU touch
            if e.where != _DISK:
                return (e.is_error,
                        bytes(e.buf) if e.where == _SHM else e.buf)
            try:
                payload = self._read_spill_fused(e, object_id)
            except (ObjectCorruptedError, OSError, ValueError) as err:
                # failed digest, torn header, or vanished file: the
                # replica is unservable — discard it (count a digest
                # failure; I/O errors are their own story)
                del self._entries[object_id]
                self.num_corrupt_dropped += 1
                try:
                    os.unlink(e.path)
                except OSError as unlink_err:
                    logger.debug("unlinking corrupt spill %s failed: "
                                 "%r", e.path, unlink_err)
                if isinstance(err, ObjectCorruptedError):
                    raise
                integrity.record_corruption("spill_restore")
                raise ObjectCorruptedError(
                    object_id.hex(), "spill_restore",
                    f"spill replica of {object_id.hex()[:16]} "
                    f"unreadable: {err!r}") from err
            self.num_restored += 1
            if e.size <= self.capacity:
                path = e.path
                self._reclaim_locked(e.size)
                self._entries[object_id] = self._admit_locked(
                    object_id, payload, e.is_error, e.primary, e.crc)
                try:
                    os.unlink(path)
                except OSError as err:
                    # orphaned spill file; the dead-owner sweep or
                    # delete() retires it later
                    logger.debug("removing spill file %s after restore "
                                 "failed: %r", path, err)
            return (e.is_error, payload)

    def pin(self, object_id: bytes) -> Optional[dict]:
        """Pin + return tier metadata in one critical section, WITHOUT
        reading the payload — the zero-copy arg path pins the entry and
        hands the worker a segment key instead of bytes. Returns None
        if absent. Pair with unpin()."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return None
            e.pins += 1
            self._entries.move_to_end(object_id)
            return {"size": e.size, "is_error": e.is_error,
                    "where": e.where, "crc": e.crc,
                    "shm_path": self._shm_path_of(e)}

    def view_and_pin(self, object_id: bytes
                     ) -> Optional[Tuple[bool, memoryview, Optional[int]]]:
        """Pin + return ``(is_error, payload_view, crc)`` WITHOUT
        copying — the chunked-send source path streams straight out of
        the segment (or heap bytes) instead of bouncing GiB-scale
        payloads through ``get()``'s copy. A spilled entry is restored
        first (one verified pass) and the view taken over the restored
        bytes. Pair with unpin(); the pin keeps reclaim off the entry
        while chunks are in flight."""
        with self._cv:
            e = self._entries.get(object_id)
            if e is not None and e.where != _DISK:
                e.pins += 1
                self._entries.move_to_end(object_id)
                return e.is_error, memoryview(e.buf), e.crc
        got = self.get(object_id)  # disk: restore (re-admits + verifies)
        if got is None:
            return None
        with self._cv:
            e = self._entries.get(object_id)
            if e is None:
                return None
            e.pins += 1
            if e.where != _DISK and e.buf is not None:
                return e.is_error, memoryview(e.buf), e.crc
            # stayed on disk (bigger than the store): the restored copy
            # got[1] is heap-held by us alone; the pin is still taken so
            # unpin stays symmetrical
            return e.is_error, memoryview(got[1]), e.crc

    def _shm_path_of(self, e: _Entry) -> Optional[str]:
        """Path of the segment that actually holds an shm-tier entry's
        bytes: this store's own segment normally, the OWNER's for an
        adopted mapping — so zero-copy readers and outward offers always
        name a segment where ``shm_key(oid)`` resolves."""
        if e.where != _SHM:
            return None
        return e.seg_path if e.seg_path is not None else self.shm_path

    def adopt_shm(self, object_id: bytes, size: int,
                  is_error: bool = False, primary: bool = True) -> bool:
        """Adopt an object a worker process already created+sealed in
        this node's segment under shm_key(object_id) — the plasma write
        path (workers create directly in the store; the raylet only
        pins). No payload bytes cross any process boundary."""
        if self._shm is None:
            return False
        key = shm_key(object_id)
        with self._cv:
            if object_id in self._entries:
                # already resident (a retry raced us): the worker-made
                # copy is an orphan unless the resident entry itself is
                # the shm entry under this key
                if self._entries[object_id].where != _SHM:
                    try:
                        self._shm.delete(key)
                    except Exception as e:
                        logger.debug("deleting orphaned worker copy of "
                                     "%s failed: %r",
                                     object_id.hex()[:8], e)
                return True
            pinned = self._shm.get_buffer(key)  # refcount pin
            if pinned is None:
                return False
            # integrity: a worker that wrote the entry with the plane
            # on appended a crc trailer — verify the payload BEFORE
            # adopting it as this node's primary copy (the seam where a
            # dying worker's half-written result would otherwise enter
            # the store). A length matching neither layout is a stale
            # or foreign entry: refuse it.
            payload_view, crc = integrity.split_shm(pinned, size)
            if payload_view is None:
                self._shm.release(key)
                return False
            if crc is not None:
                try:
                    integrity.verify(payload_view, crc, "adopt_shm",
                                     object_id)
                except ObjectCorruptedError:
                    self.num_corrupt_dropped += 1
                    payload_view.release()
                    self._shm.release(key)
                    try:
                        self._shm.delete(key)
                    except Exception as e:
                        logger.debug("deleting corrupt worker copy of "
                                     "%s failed: %r",
                                     object_id.hex()[:8], e)
                    return False
            self._reclaim_locked(size)
            self.total_bytes += size
            self._entries[object_id] = _Entry(is_error, _SHM,
                                              payload_view, size,
                                              primary, crc=crc)
            self._cv.notify_all()
        return True

    # --------------------------------------- data plane: streamed receive
    def begin_receive(self, object_id: bytes, size: int,
                      is_error: bool = False,
                      crc: Optional[int] = None
                      ) -> Optional[ReceiveHandle]:
        """Open a streamed receive: preallocate the object's FINAL
        bytes (shm segment entry when eligible, heap otherwise) and
        return a :class:`ReceiveHandle` whose ``view`` chunk frames
        ``recv_into`` directly — socket to sealed segment offset in one
        copy, no assembly buffer. Returns None when the object is
        already resident (the push is a duplicate). A half-open receive
        of the same id is superseded (torn sender, re-push won the
        race). The bytes are reserved against capacity from here —
        reclaim runs now, not at seal."""
        with self._cv:
            if object_id in self._entries:
                return None
            old = self._receiving.pop(object_id, None)
            if old is not None:
                self._discard_rx_locked(old)
            h = ReceiveHandle(object_id, size, is_error, crc)
            h._trailer = (integrity.TRAILER_SIZE
                          if crc is not None or integrity.enabled()
                          else 0)
            if (self._shm is not None and size >= self.shm_min_bytes
                    and size <= self.capacity):
                try:
                    key = shm_key(object_id)
                    self._reclaim_locked(size)
                    try:
                        buf = self._shm.create(key, size + h._trailer)
                    except KeyError:
                        # leftover unsealed entry of a torn receive
                        # under this key: unsealed deletes free
                        # immediately (shm_store.cpp delete semantics)
                        self._shm.delete(key)
                        buf = self._shm.create(key, size + h._trailer)
                    h._buf = buf
                    h.view = buf[:size]
                    h.shm = True
                except (MemoryError, KeyError, OSError) as e:
                    logger.debug("shm receive alloc of %s (%d bytes) "
                                 "fell back to heap: %r",
                                 object_id.hex()[:8], size, e)
            if h.view is None:
                h.view = memoryview(bytearray(size))
            self.total_bytes += size
            self._receiving[object_id] = h
            return h

    def seal_receive(self, h: ReceiveHandle, crc: Optional[int] = None,
                     primary: bool = False) -> bool:
        """Admit a completed receive as a resident entry. ``crc`` is
        the receiver's RUNNING digest (``integrity.checksum_update``
        folded over the chunks as they landed — the fused single pass);
        it is checked against the digest the sender declared at begin,
        and on mismatch the receive is torn down and
        :class:`~ray_tpu.exceptions.ObjectCorruptedError` raised.
        Returns False when this receive was superseded meanwhile."""
        final_crc = crc if crc is not None else h.crc
        with self._cv:
            st = self._receiving.get(h.object_id)
            if st is not h:
                return False
            del self._receiving[h.object_id]
            if h.object_id in self._entries:
                # a concurrent pull beat the push: resident wins
                self._discard_rx_locked(h)
                return True
            if (h.crc is not None and crc is not None
                    and crc != h.crc and integrity.enabled()):
                self._discard_rx_locked(h)
                self.num_corrupt_dropped += 1
                integrity.record_corruption("push_receive")
                raise ObjectCorruptedError(
                    h.object_id.hex(), "push_receive",
                    f"streamed receive of {h.object_id.hex()[:16]} "
                    f"failed its end-to-end digest "
                    f"(expected {h.crc:#010x}, got {crc:#010x}); "
                    f"half-assembled replica discarded")
            if h.shm:
                try:
                    key = shm_key(h.object_id)
                    if h._trailer:
                        if final_crc is None:  # safety net: cold pass
                            final_crc = integrity.checksum(h.view)
                        h._buf[h.size:] = integrity.pack_trailer(
                            final_crc)
                    h.view.release()
                    h._buf.release()
                    h.view = h._buf = None
                    self._shm.seal(key)
                    pinned = self._shm.get_buffer(key)
                    entry = _Entry(h.is_error, _SHM, pinned[:h.size],
                                   h.size, primary, crc=final_crc)
                except Exception:
                    self._discard_rx_locked(h)
                    raise
            else:
                data = bytes(h.view)
                h.view = None
                if h.size > self.capacity:
                    entry = self._spill_payload(h.object_id, data,
                                                h.is_error, primary,
                                                final_crc)
                    self.total_bytes -= h.size  # disk doesn't count
                else:
                    entry = _Entry(h.is_error, _MEM, data, h.size,
                                   primary, crc=final_crc)
            self._entries[h.object_id] = entry
            self._cv.notify_all()
        return True

    def abort_receive(self, object_id: bytes) -> bool:
        """Tear down a half-assembled receive (sender died mid-stream,
        a chunk failed its digest, or the stale sweep fired): the
        unsealed segment entry is freed immediately and the reserved
        bytes returned to capacity. Counted. Returns False when no
        receive of this id is open."""
        with self._cv:
            h = self._receiving.pop(object_id, None)
            if h is None:
                return False
            self._discard_rx_locked(h)
            self.num_rx_aborted += 1
        return True

    def sweep_stale_receives(self, max_age_s: float) -> List[bytes]:
        """Abort receives with no chunk progress for ``max_age_s`` —
        the raylet's heartbeat calls this so a sender that vanished
        mid-broadcast cannot strand reserved segment bytes. Returns
        the object ids torn down."""
        now = time.monotonic()
        out: List[bytes] = []
        with self._cv:
            for oid, h in list(self._receiving.items()):
                if now - h.t_last >= max_age_s:
                    del self._receiving[oid]
                    self._discard_rx_locked(h)
                    self.num_rx_aborted += 1
                    out.append(oid)
        return out

    def _discard_rx_locked(self, h: ReceiveHandle) -> None:
        if h.shm:
            for v in (h.view, h._buf):
                try:
                    if v is not None:
                        v.release()
                except Exception as e:
                    logger.debug("releasing receive view of %s failed: "
                                 "%r", h.object_id.hex()[:8], e)
            try:
                # unsealed entries free immediately, writer ref or not
                self._shm.delete(shm_key(h.object_id))
            except Exception as e:
                logger.debug("freeing aborted receive of %s failed: %r",
                             h.object_id.hex()[:8], e)
        h.view = None
        h._buf = None
        self.total_bytes -= h.size

    # --------------------------------------- data plane: segment adoption
    def adopt_remote_shm(self, object_id: bytes, shm_path: str,
                         size: int, is_error: bool = False,
                         crc: Optional[int] = None,
                         primary: bool = False) -> bool:
        """Adopt a same-host peer's sealed segment entry as a local
        replica by MAPPING it, not copying it — the plasma posture of
        one physical object copy per host. The pin rides the segment's
        cross-process refcount, so the owner deleting the object defers
        the free until our release (shm_store.cpp kPendingDelete).
        Verification is O(1): the trailer's structural check plus an
        integer compare of its digest against the offer's — the fused
        put-time digest already vouches for the bytes, so
        ``integrity_verify_shm_reads`` costs nothing on this path.
        Returns False on any failure (caller falls back to the copying
        stream path); a path that doesn't exist is the not-same-host
        test itself."""
        if self._shm is None or shm_path is None:
            return False
        if shm_path == self.shm_path:
            # our own segment: the object is either already ours or
            # adoptable through the worker-write path
            return self.adopt_shm(object_id, size, is_error, primary)
        seg = attach_shm(shm_path)
        if seg is None:
            return False
        key = shm_key(object_id)
        with self._cv:
            if object_id in self._entries:
                return True
            try:
                pinned = seg.get_buffer(key)
            except Exception as e:
                logger.debug("pinning %s in peer segment %s failed: %r",
                             object_id.hex()[:8], shm_path, e)
                return False
            if pinned is None:
                return False
            payload_view, seg_crc = integrity.split_shm(pinned, size)
            if payload_view is None:
                # stale or foreign entry under this key: refuse
                seg.release(key)
                return False
            if seg_crc is not None and crc is not None:
                if seg_crc != crc:
                    # the offer's digest disagrees with the segment
                    # trailer — one of the copies is wrong; refuse
                    # without a byte pass and let recovery re-source
                    integrity.record_corruption("adopt_remote")
                    self.num_corrupt_dropped += 1
                    payload_view.release()
                    seg.release(key)
                    return False
            elif crc is not None and integrity.enabled():
                # trailerless producer: one verified pass before
                # serving a peer's bytes as ours
                try:
                    integrity.verify(payload_view, crc, "adopt_remote",
                                     object_id)
                except ObjectCorruptedError:
                    self.num_corrupt_dropped += 1
                    payload_view.release()
                    seg.release(key)
                    return False
            self._entries[object_id] = _Entry(
                is_error, _SHM, payload_view, size, primary,
                crc=crc if crc is not None else seg_crc,
                seg=seg, seg_path=shm_path)
            self.num_shm_adopts += 1
            self._cv.notify_all()
        return True

    def get_and_pin(self, object_id: bytes
                    ) -> Optional[Tuple[bool, bytes]]:
        """get() + pin in one critical section: the caller is about to
        use the payload as a task argument, and a concurrent put's
        reclaim must not drop it between lookup and use. Pair with
        unpin()."""
        with self._cv:
            e = self._entries.get(object_id)
            if e is None:
                return None
            e.pins += 1
        try:
            result = self.get(object_id)
        except BaseException:
            self.unpin(object_id)
            raise
        if result is None:  # deleted between pin and read
            self.unpin(object_id)
        return result

    def unpin(self, object_id: bytes) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None:
                if e.pins > 0:
                    e.pins -= 1
                return
            e = self._condemned.get(object_id)
            if e is not None:
                if e.pins > 0:
                    e.pins -= 1
                if e.pins == 0:  # last pin on a deleted entry: free it
                    del self._condemned[object_id]
                    self._finalize_delete_locked(object_id, e)

    def wait(self, object_id: bytes, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self._cv:
            while object_id not in self._entries:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            return True

    def delete(self, object_id: bytes) -> None:
        """Remove an object. A PINNED entry (a task is using it as an
        argument right now) is condemned instead: it stops being
        gettable immediately, but its bytes survive until the last
        unpin — mirroring both the C store's deferred delete and
        plasma's delete-while-in-use rule."""
        with self._lock:
            e = self._entries.pop(object_id, None)
            if e is None:
                return
            if e.pins > 0:
                self._condemned[object_id] = e
                return
            self._finalize_delete_locked(object_id, e)

    def _finalize_delete_locked(self, object_id: bytes,
                                e: _Entry) -> None:
        self._drop_tier_locked(object_id, e)
        if e.where == _DISK and e.path:
            try:
                os.unlink(e.path)
            except OSError as err:
                logger.debug("removing spill file %s on delete of %s "
                             "failed: %r", e.path,
                             object_id.hex()[:8], err)

    def close(self) -> None:
        with self._cv:
            # tear down half-open receives and drop our pins in PEER
            # segments (their owners' deferred deletes are waiting on
            # our release — holding them past close would strand the
            # owner's bytes until process exit)
            for h in self._receiving.values():
                self._discard_rx_locked(h)
            self._receiving.clear()
            for oid in [o for o, e in self._entries.items()
                        if e.seg is not None]:
                self._drop_tier_locked(oid)
                del self._entries[oid]
        if self._shm is not None:
            try:
                self._shm.close(unlink=True)
            except Exception as e:
                # stale-segment sweep reclaims whatever this leaves
                logger.debug("shm segment close failed: %r", e)
            self._shm = None


class PushManager:
    """Outbound push throttle (reference: object_manager/push_manager.h —
    dedup of concurrent pushes of the same object to the same node and a
    cap on chunks in flight).

    ``push`` enqueues (object_id, dest) unless that pair is already
    queued or being sent; at most ``max_inflight`` destination transfers
    run at once, each chunked with at most ``max_chunks_in_flight``
    unacknowledged chunk RPCs (the pipelining knob)."""

    def __init__(self, send_fn: Callable[[bytes, str], None],
                 max_inflight: int = 4,
                 max_queued: Optional[int] = None):
        from ray_tpu._private.config import Config
        from ray_tpu.cluster.threads import ThreadRegistry

        self._send_fn = send_fn
        self._max_inflight = max_inflight
        self._max_queued = (max_queued if max_queued is not None
                            else Config.instance().push_manager_max_queued)
        self._lock = threading.Lock()
        self._inflight: set = set()      # (object_id, dest) being sent
        self._queue: "OrderedDict[Tuple[bytes, str], None]" = OrderedDict()
        self._active = 0
        # transfer workers spawn through the registry: they are named,
        # a hung sender surfaces in join_all() by name, and dead ones
        # are pruned on each spawn (raycheck RC09)
        self._threads = ThreadRegistry("push-manager")
        self.num_pushed = 0
        self.num_deduped = 0
        # overload plane: pushes shed because the outbound queue was at
        # its bound (a slow receiver must not grow the queue forever)
        self.num_shed = 0

    def join_all(self, timeout: float = 5.0) -> list:
        """Join outstanding transfer workers (teardown observability);
        returns the names still running."""
        return self._threads.join_all(timeout)

    def push(self, object_id: bytes, dest: str,
             downstream: Optional[list] = None) -> bool:
        """Schedule a push; returns False if it was already in flight
        (the dedup of PushManager::StartPush) or the bounded outbound
        queue shed it (the caller can re-request; broadcast's
        confirm-and-retry loop already does). ``downstream`` is a
        chunk-tree subtree plan ([[address, subtree], ...]) relayed to
        the send function — the receiver becomes an interior node and
        forwards onward (dedup stays keyed on (object, dest): a second
        request for the same pair rides the in-flight transfer)."""
        key = (object_id, dest)
        with self._lock:
            if key in self._inflight or key in self._queue:
                self.num_deduped += 1
                return False
            if len(self._queue) >= self._max_queued:
                self.num_shed += 1
                return False
            self._queue[key] = downstream
            self._pump_locked()
        return True

    def _pump_locked(self) -> None:
        while self._active < self._max_inflight and self._queue:
            key, downstream = self._queue.popitem(last=False)
            self._inflight.add(key)
            self._active += 1
            self._threads.spawn(
                self._run, f"push-{key[0].hex()[:8]}",
                args=(key, downstream))

    def _run(self, key: Tuple[bytes, str],
             downstream: Optional[list] = None) -> None:
        try:
            if downstream:
                self._send_fn(key[0], key[1], downstream)
            else:  # legacy two-arg send functions keep working
                self._send_fn(*key)
            with self._lock:  # worker threads race this counter
                self.num_pushed += 1
        except Exception as e:
            logger.info("push of %s to %s failed: %r",
                        key[0].hex()[:8], key[1], e)
        finally:
            with self._lock:
                self._inflight.discard(key)
                self._active -= 1
                self._pump_locked()

    def stats(self) -> dict:
        with self._lock:
            return {"inflight": len(self._inflight),
                    "queued": len(self._queue),
                    "num_pushed": self.num_pushed,
                    "num_deduped": self.num_deduped,
                    "num_shed": self.num_shed}
