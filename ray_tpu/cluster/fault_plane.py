"""FaultPlane — deterministic, scenario-driven fault injection for the
framed-TCP RPC substrate (cluster/rpc.py).

The reference's fault-tolerance machinery (heartbeat death detection,
GCS client retry on failover, PG 2PC rollback, lineage reconstruction)
is only trustworthy under failure modes a SIGKILL cannot produce:
delayed frames, duplicated deliveries, truncated writes, half-open
connections, one-way partitions. This module injects exactly those at
the RPC seams, from a single integer seed, so any failing schedule
replays bit-for-bit (the FoundationDB simulation-testing / Jepsen-nemesis
posture, scoped to this repo's process tier).

## Activation

Per process, via the environment::

    RAY_TPU_FAULT_PLAN='{"seed": 7, "rules": [...]}'   # inline JSON
    RAY_TPU_FAULT_PLAN=/path/to/plan.json              # or a file

(also honored: the ``fault_plan`` Config flag / ``RAY_TPU_fault_plan``),
or programmatically with ``install_plane(FaultPlane(plan))`` for
in-process (driver-side) injection. ``ProcessCluster`` forwards
per-node/per-GCS plans into child environments (process_cluster.py).

## Plan format

``{"seed": <int>, "rules": [<rule>, ...]}`` where each rule is::

    {
      "src_role":  "*",         # fnmatch vs this process's role
                                # (gcs | raylet | driver | worker | *)
      "dst":       "*",         # fnmatch vs "host:port" of the peer
                                # (direction "spill": the store tier,
                                # "byte_store" | "memory_store")
      "method":    "*",         # fnmatch vs the RPC method name
                                # (direction "spill": the object hex)
      "direction": "request",   # request | reply | connect | handler
                                # | spill
      "action":    "drop",      # drop | partition | refuse | delay |
                                # duplicate | truncate | stall |
                                # corrupt
      "prob":      1.0,         # per-event firing probability (seeded)
      "after":     0,           # skip the first N matching events
      "count":     null,        # fire at most N times (null = forever)
      "delay_ms":  [lo, hi],    # seeded jitter range for "delay"
      "phase":     "connect",   # connect faults: connect | post-hello
      "start_s":   0.0,         # wall-clock window (plane birth = 0);
      "stop_s":    null         # healing partitions use stop_s
    }

Actions by direction:
  connect  — refuse (connection refused), drop (phase "post-hello":
             handshake completes, then the socket dies — a half-open
             peer), delay (slow accept).
  request  — drop/partition (frame silently lost: the caller times out,
             exactly like a one-way partition), delay (seeded jitter
             before the write), duplicate (the frame is written twice —
             the server executes the method twice, exercising handler
             idempotency), truncate (a prefix of the frame is written
             and the socket is cut mid-frame).
  reply    — same menu, applied to the server's reply frames (the other
             one-way partition: requests arrive, acks vanish).
  handler  — stall (seeded ``delay_ms`` jitter INSIDE the server's
             dispatch, after admission but before the handler body):
             the request occupies a bounded dispatch-pool slot for the
             stall's duration, so a stalled GCS/raylet builds a real
             admission queue and sheds — the deterministic overload
             scenario behind the retry-storm regression tests
             (tests/test_overload.py).
  request/reply also carry ``corrupt``: ONE seeded byte of the frame
             body is XOR-flipped (tail-biased, so on large chunk frames
             the flip lands in the payload bytes, not the pickle
             structure) — the silent-data-corruption scenario the
             integrity plane (cluster/integrity.py) detects at its
             checksum seams.
  spill    — corrupt (the only action for this direction): a seeded
             byte of the payload WRITTEN to a spill file is flipped
             after the header digest was computed, modeling at-rest
             corruption / a torn write; ``dst`` is the store tier
             ("byte_store" | "memory_store") and ``method`` the object
             id hex, so one object's flip replays per-stream.

## Determinism contract

Every probabilistic decision (prob draws, delay jitter) comes from a
per-(rule, dst, method) RNG seeded as blake2(seed, rule_index, dst,
method): a stream's Kth matching event always gets the same decision
regardless of how other streams interleave. ``after``/``count`` windows
count per stream, so they are deterministic in event space too.
``start_s``/``stop_s`` windows are wall-clock (needed for
partition-heals-after-T scenarios) and therefore only approximately
replayable — schedules that must replay exactly use event-count windows.
Reply-direction raw stream chunks (the "R" frames of ``get_object``
transfers) are not faulted; the control frames around them are.
Request-direction raw data frames (the data plane's ``push_chunk_data``)
ARE faulted — ``corrupt`` flips a seeded payload byte on a COPY of the
outgoing chunk (the sender's pinned shm source is never mutated), which
is how the chunk-level crc seam is exercised (tests/test_data_plane.py).

Failing scenarios print ``describe()`` — seed + plan — so the schedule
can be re-run verbatim (tests/test_fault_injection.py wires this into
its assert path).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import random
import threading
import time
from collections import deque
from fnmatch import fnmatchcase
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

ACTIONS = ("drop", "partition", "refuse", "delay", "duplicate",
           "truncate", "stall", "corrupt")
DIRECTIONS = ("request", "reply", "connect", "handler", "spill")


class FaultRule:
    __slots__ = ("index", "src_role", "dst", "method", "direction",
                 "action", "prob", "after", "count", "delay_ms", "phase",
                 "start_s", "stop_s", "truncate_bytes")

    def __init__(self, index: int, spec: Dict[str, Any]):
        self.index = index
        self.src_role = spec.get("src_role", "*")
        self.dst = spec.get("dst", "*")
        self.method = spec.get("method", "*")
        self.direction = spec.get("direction", "request")
        self.action = spec["action"]
        self.prob = float(spec.get("prob", 1.0))
        self.after = int(spec.get("after", 0))
        self.count = spec.get("count")
        self.delay_ms = spec.get("delay_ms", [0, 0])
        self.phase = spec.get("phase", "connect")
        self.start_s = float(spec.get("start_s", 0.0))
        self.stop_s = spec.get("stop_s")
        # how much of the frame still reaches the wire before the cut;
        # None = half the frame (header always lands, so the peer's
        # reader is mid-frame when the connection dies)
        self.truncate_bytes = spec.get("truncate_bytes")
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r}")
        if self.direction not in DIRECTIONS:
            raise ValueError(
                f"unknown fault direction {self.direction!r}")
        if (self.action == "stall") != (self.direction == "handler"):
            raise ValueError(
                "stall faults pair with direction 'handler' (and "
                "'handler' only carries stalls): the slowdown happens "
                "inside the server's dispatch, not on the wire")
        if self.direction == "spill" and self.action != "corrupt":
            raise ValueError(
                "direction 'spill' only carries 'corrupt': spill files "
                "are written locally — there is nothing to drop or "
                "delay on a wire")
        if self.action == "corrupt" and self.direction not in (
                "request", "reply", "spill"):
            raise ValueError(
                "corrupt faults flip payload bytes: pair with "
                "direction 'request', 'reply', or 'spill'")

    def matches(self, role: str, dst: str, method: str) -> bool:
        return (fnmatchcase(role, self.src_role)
                and fnmatchcase(dst, self.dst)
                and fnmatchcase(method, self.method))


class _Stream:
    """Per-(rule, dst, method) decision stream: its own RNG + counters,
    so one stream's schedule is independent of every other stream's
    interleaving."""

    __slots__ = ("rng", "seen", "fired")

    def __init__(self, seed: int):
        self.rng = random.Random(seed)
        self.seen = 0
        self.fired = 0


def _stream_seed(seed: int, rule_index: int, dst: str,
                 method: str) -> int:
    h = hashlib.blake2b(
        f"{seed}|{rule_index}|{dst}|{method}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big")


class FaultPlane:
    """One process's active fault schedule. Thread-safe; all decisions
    funnel through :meth:`decide` under one lock (RPC-frame granularity
    — the injection cost is dwarfed by the frame's own pickling)."""

    def __init__(self, plan: Dict[str, Any]):
        self.seed = int(plan.get("seed", 0))
        self.plan = plan
        self.rules: List[FaultRule] = [
            FaultRule(i, spec)
            for i, spec in enumerate(plan.get("rules", []))]
        self._streams: Dict[Tuple[int, str, str], _Stream] = {}
        self._lock = threading.Lock()
        self._birth = time.monotonic()
        # fired-event journal: (rule_index, direction, dst, method,
        # event_index_in_stream, action, param) — the replay fingerprint
        self.events: deque = deque(maxlen=10_000)

    # ------------------------------------------------------------ decisions
    def decide(self, direction: str, dst: str,
               method: str = "") -> Optional[Dict[str, Any]]:
        """First firing rule wins; None = no fault. The returned dict is
        ``{"action": ..., "rule": idx}`` plus action params
        (``seconds`` for delay, ``phase`` for connect faults,
        ``truncate_bytes`` for truncate)."""
        role = process_role()
        now = time.monotonic() - self._birth
        with self._lock:
            for rule in self.rules:
                if rule.direction != direction:
                    continue
                if not rule.matches(role, dst, method):
                    continue
                if now < rule.start_s:
                    continue
                if rule.stop_s is not None and now >= rule.stop_s:
                    continue
                key = (rule.index, dst, method)
                stream = self._streams.get(key)
                if stream is None:
                    stream = _Stream(
                        _stream_seed(self.seed, rule.index, dst, method))
                    self._streams[key] = stream
                stream.seen += 1
                if stream.seen <= rule.after:
                    continue
                if rule.count is not None and stream.fired >= rule.count:
                    continue
                if stream.rng.random() > rule.prob:
                    continue
                stream.fired += 1
                out: Dict[str, Any] = {"action": rule.action,
                                       "rule": rule.index}
                param: Any = None
                if rule.action in ("delay", "stall"):
                    lo, hi = rule.delay_ms
                    param = (lo + stream.rng.random() * (hi - lo)) / 1000.0
                    out["seconds"] = param
                elif rule.action == "truncate":
                    param = rule.truncate_bytes
                    out["truncate_bytes"] = param
                elif rule.action == "corrupt":
                    # seeded flip: position fraction + a nonzero XOR
                    # mask, both per-stream deterministic
                    out["frac"] = stream.rng.random()
                    out["xor"] = 1 + int(stream.rng.random() * 254)
                    param = (round(out["frac"], 6), out["xor"])
                elif direction == "connect":
                    out["phase"] = rule.phase
                self.events.append((rule.index, direction, dst, method,
                                    stream.seen, rule.action, param))
                return out
        return None

    # --------------------------------------------------------------- stats
    def fired(self, rule_index: Optional[int] = None) -> int:
        with self._lock:
            return sum(
                s.fired for (idx, _, _), s in self._streams.items()
                if rule_index is None or idx == rule_index)

    def schedule(self) -> List[tuple]:
        """The fired-event journal as a list — two planes driven through
        the same event sequence with the same seed produce identical
        schedules (the replay contract)."""
        with self._lock:
            return list(self.events)

    def describe(self) -> str:
        """Replay recipe: printed by failing fault scenarios."""
        return (f"replay: seed={self.seed} "
                f"RAY_TPU_FAULT_PLAN='{json.dumps(self.plan)}'")


# --------------------------------------------------------------------------
# process-wide plane + role
# --------------------------------------------------------------------------

_plane: Optional[FaultPlane] = None
_env_checked = False
_install_lock = threading.Lock()
_role: Optional[str] = None


def process_role() -> str:
    """This process's role for src_role matching (gcs | raylet | driver
    | worker). Settable by the server mains; defaults from
    RAY_TPU_PROCESS_ROLE, else 'driver'."""
    global _role
    if _role is None:
        _role = os.environ.get("RAY_TPU_PROCESS_ROLE", "driver")
    return _role


def set_process_role(role: str) -> None:
    global _role
    _role = role


def load_plan(raw: str) -> Dict[str, Any]:
    """Parse a plan from inline JSON or a file path."""
    raw = raw.strip()
    if not raw.startswith("{"):
        with open(raw) as f:
            raw = f.read()
    return json.loads(raw)


def get_plane() -> Optional[FaultPlane]:
    """The process's active plane, lazily loaded from the environment on
    first use. Returns None (the overwhelmingly common case) when no
    plan is configured — callers gate all injection on this."""
    global _plane, _env_checked
    if _plane is not None or _env_checked:
        return _plane
    with _install_lock:
        if _env_checked:
            return _plane
        raw = os.environ.get("RAY_TPU_FAULT_PLAN", "")
        if not raw:
            try:
                from ray_tpu._private.config import Config

                raw = Config.instance().fault_plan
            except Exception:  # config import cycles at interpreter exit
                raw = ""
        if raw:
            try:
                _plane = FaultPlane(load_plan(raw))
                logger.warning("fault plane ACTIVE: %s",
                               _plane.describe())
            except Exception:
                logger.exception("invalid RAY_TPU_FAULT_PLAN; ignoring")
        _env_checked = True
    return _plane


def install_plane(plane: Optional[FaultPlane]) -> Optional[FaultPlane]:
    """Programmatic (driver/in-process) activation. Returns the plane."""
    global _plane, _env_checked
    with _install_lock:
        _plane = plane
        _env_checked = True
    return plane


def clear_plane() -> None:
    """Deactivate and forget the cached env decision (tests)."""
    global _plane, _env_checked
    with _install_lock:
        _plane = None
        _env_checked = False


def derive_rng(namespace: str) -> random.Random:
    """Explicit RNG stream for a runtime subsystem (raycheck RC03: no
    module-level ``random.*`` draws in cluster/scheduler code). When a
    fault plane is active the stream is derived from the plan's single
    integer seed + the namespace — backoff jitter and replica-shuffle
    decisions then replay bit-for-bit with the fault schedule itself;
    with no plane it is entropy-seeded like any fresh ``Random()``.

    Namespace convention: ``"<subsystem>|<instance>"``, e.g.
    ``"rpc-backoff|127.0.0.1:6379"`` — two instances never share a
    stream, so one consumer's draw count cannot perturb another's."""
    plane = get_plane()
    if plane is None:
        return random.Random()
    h = hashlib.blake2b(f"{plane.seed}|{namespace}".encode(),
                        digest_size=8)
    return random.Random(int.from_bytes(h.digest(), "big"))


def apply_corruption(data, fault: Dict[str, Any],
                     tail_bias: bool = False) -> bytearray:
    """XOR-flip ONE seeded byte of ``data`` per a fired ``corrupt``
    decision. ``tail_bias=True`` confines the flip to the second half
    of the buffer — on a pickled chunk frame the header/pickle
    structure sits up front, so a tail flip corrupts the payload bytes
    (silent wrongness, the case checksums exist for) rather than the
    framing (which would fail loudly on its own)."""
    buf = bytearray(data)
    if not buf:
        return buf
    lo = len(buf) // 2 if tail_bias else 0
    span = max(1, len(buf) - lo)
    off = lo + min(span - 1, int(fault["frac"] * span))
    buf[off] ^= fault["xor"]
    return buf


def plan_env(plan: Dict[str, Any]) -> Dict[str, str]:
    """Environment fragment activating ``plan`` in a child process
    (ProcessCluster's add_node/gcs_env take this directly)."""
    return {"RAY_TPU_FAULT_PLAN": json.dumps(plan)}


# --------------------------------------------------------------------------
# StormPlan — seeded composite fault/overload storms
# --------------------------------------------------------------------------

# Order matters: _derive draws in declaration order, so new kinds must
# APPEND (and derive after the existing ones) to keep the draw
# sequence — and therefore existing storms' timelines — stable.
STORM_KINDS = ("stall_burst", "drop_burst", "corrupt_burst",
               "partition_burst", "kill_replica", "kill_raylet",
               "kill_mid_frame", "partition_mid_tree", "preempt_node")


class StormPlan:
    """A seeded storm TIMELINE over a serve cluster: bursts of the
    existing rule kinds (handler stalls against the GCS and the serve
    replicas, request drops, reply-path corruption against the serve
    response seam, a one-way partition window) PLUS process-kill
    events against serve replicas / raylets — all derived from ONE
    integer seed, so a failing storm replays bit-for-bit like any
    other fault plan (the Jepsen-nemesis posture, composed).

    The wire-rule half feeds :class:`FaultPlane` directly
    (``FaultPlane(storm.plan())``); the kill half is a sorted event
    list the storm driver (bench.py's serve row, the
    ``serve_resilience`` tests) applies against live replica handles /
    raylet processes at the scheduled offsets. Two plans built from
    the same (seed, duration, intensity, kinds) are identical —
    :meth:`timeline` is the canonical fingerprint the determinism test
    pins.
    """

    def __init__(self, seed: int, duration_s: float = 6.0,
                 intensity: float = 1.0,
                 kinds: Optional[Tuple[str, ...]] = None):
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.intensity = float(intensity)
        self.kinds = tuple(kinds) if kinds is not None else STORM_KINDS
        unknown = set(self.kinds) - set(STORM_KINDS)
        if unknown:
            raise ValueError(f"unknown storm kinds {sorted(unknown)}; "
                             f"choose from {STORM_KINDS}")
        rng = random.Random(_stream_seed(self.seed, -1, "storm", ""))
        self.rules: List[Dict[str, Any]] = []
        self.kills: List[Dict[str, Any]] = []
        self._derive(rng)

    def _window(self, rng: random.Random) -> Tuple[float, float]:
        """A burst window inside the storm, never butting the end (the
        tail must show recovery)."""
        span = max(0.2, self.duration_s * (0.15 + 0.25 * rng.random()))
        start = rng.random() * max(0.05, self.duration_s - span - 0.2)
        return round(start, 3), round(start + span, 3)

    def _n_bursts(self, rng: random.Random) -> int:
        return max(1, round(self.intensity * (1 + rng.randrange(2))))

    def _derive(self, rng: random.Random) -> None:
        # Derivation order is FIXED (kind declaration order): the draw
        # sequence, and therefore the whole timeline, is a pure
        # function of the constructor arguments.
        for kind in STORM_KINDS:
            if kind not in self.kinds:
                continue
            if kind == "stall_burst":
                for _ in range(self._n_bursts(rng)):
                    start, stop = self._window(rng)
                    # one burst against the control plane (GCS/raylet
                    # handlers), one against the serve replicas' own
                    # request slots
                    dst = "serve::*" if rng.random() < 0.5 else "*"
                    self.rules.append({
                        "action": "stall", "direction": "handler",
                        "dst": dst, "method": "*",
                        "prob": round(0.4 + 0.5 * rng.random(), 3),
                        "delay_ms": [20, int(60 + 140 * self.intensity)],
                        "start_s": start, "stop_s": stop})
            elif kind == "drop_burst":
                for _ in range(self._n_bursts(rng)):
                    start, stop = self._window(rng)
                    self.rules.append({
                        "action": "drop", "direction": "request",
                        "dst": "*", "method": "*",
                        "prob": round(0.15 + 0.25 * rng.random(), 3),
                        "start_s": start, "stop_s": stop})
            elif kind == "corrupt_burst":
                for _ in range(self._n_bursts(rng)):
                    start, stop = self._window(rng)
                    # the serve response seam (replica._respond) — the
                    # silent-wrong-answer ingredient the resilience
                    # plane's reply digest catches
                    self.rules.append({
                        "action": "corrupt", "direction": "reply",
                        "dst": "serve::*", "method": "*",
                        "prob": round(0.3 + 0.5 * rng.random(), 3),
                        "start_s": start, "stop_s": stop})
            elif kind == "partition_burst":
                start, stop = self._window(rng)
                self.rules.append({
                    "action": "partition", "direction": "request",
                    "dst": "*", "method": "*", "prob": 1.0,
                    "start_s": start, "stop_s": stop})
            elif kind in ("kill_replica", "kill_raylet"):
                n = self._n_bursts(rng)
                for _ in range(n):
                    t = 0.1 + rng.random() * max(
                        0.1, self.duration_s * 0.7)
                    self.kills.append({
                        "t": round(t, 3),
                        "target": ("replica" if kind == "kill_replica"
                                   else "raylet"),
                        # driver resolves ordinal mod the live set size
                        "ordinal": rng.randrange(64)})
            elif kind == "kill_mid_frame":
                # Batch-boundary storm: a reply-drop window over the
                # coalesced batch wire surface — frames APPLY on the
                # server, acks vanish, clients retry the whole frame —
                # with a raylet kill scheduled INSIDE the window. The
                # per-row tokens (exactly-once batch frames) must make
                # the replay idempotent; without them the same seed
                # observably double-places tasks / double-creates
                # actors.
                for _ in range(self._n_bursts(rng)):
                    start, stop = self._window(rng)
                    self.rules.append({
                        "action": "drop", "direction": "reply",
                        "dst": "*", "method": "*_batch",
                        "prob": round(0.4 + 0.4 * rng.random(), 3),
                        "start_s": start, "stop_s": stop})
                    t = start + (stop - start) * (0.2
                                                  + 0.6 * rng.random())
                    self.kills.append({
                        "t": round(t, 3), "target": "raylet",
                        "ordinal": rng.randrange(64),
                        "phase": "mid_frame"})
            elif kind == "partition_mid_tree":
                # Batch-boundary storm: sever the chunk-tree push
                # plane mid-relay for a window — interior relays go
                # unreachable with transfers in flight, exercising
                # subtree re-rooting (chunk_tree_failover_enabled) and
                # clean cut-through teardown.
                for _ in range(self._n_bursts(rng)):
                    start, stop = self._window(rng)
                    self.rules.append({
                        "action": "partition", "direction": "request",
                        "dst": "*", "method": "push_*", "prob": 1.0,
                        "start_s": start, "stop_s": stop})
            elif kind == "preempt_node":
                # Spot/preemptible eviction with a NOTICE window
                # (reference: the cloud's preemption warning -> the
                # DrainNode path). The driver delivers preempt_notice
                # to the victim raylet at t, then SIGKILLs it at
                # t + notice_s — the drain plane must migrate actors
                # and re-replicate sole-copy objects INSIDE the
                # window. Appended LAST (declaration-order contract
                # above), so pre-existing storm timelines are
                # unchanged.
                from ray_tpu._private.config import Config as _Cfg

                base_notice = _Cfg.instance().preempt_notice_s
                for _ in range(self._n_bursts(rng)):
                    t = 0.1 + rng.random() * max(
                        0.1, self.duration_s * 0.5)
                    notice = round(
                        base_notice * (0.75 + 0.5 * rng.random()), 3)
                    self.kills.append({
                        "t": round(t, 3), "target": "raylet",
                        "ordinal": rng.randrange(64),
                        "phase": "preempt", "notice_s": notice})
        self.kills.sort(key=lambda k: (k["t"], k["target"], k["ordinal"]))
        # validate every generated rule against the FaultRule contract
        # NOW: a malformed storm must fail at derivation, not mid-run
        for i, spec in enumerate(self.rules):
            FaultRule(i, spec)

    # ---------------------------------------------------------------- views
    def plan(self) -> Dict[str, Any]:
        """The wire half, directly consumable by :class:`FaultPlane`
        (and by RAY_TPU_FAULT_PLAN / plan_env for child processes)."""
        return {"seed": self.seed, "rules": [dict(r) for r in self.rules]}

    def kill_events(self) -> List[Dict[str, Any]]:
        return [dict(k) for k in self.kills]

    def timeline(self) -> List[tuple]:
        """Canonical fingerprint: every burst window and kill event as
        sorted tuples — two plans from the same seed are identical
        here (the determinism contract the tests pin)."""
        out: List[tuple] = []
        for r in self.rules:
            out.append(("rule", r["start_s"], r.get("stop_s"),
                        r["action"], r["direction"], r["dst"],
                        r["prob"]))
        for k in self.kills:
            out.append(("kill", k["t"], None, k["target"], "", "",
                        k["ordinal"]))
        out.sort(key=lambda e: (e[1], e[0], str(e[3:])))
        return out

    def describe(self) -> str:
        """Replay recipe: printed by failing storm scenarios."""
        return (f"storm replay: RAY_TPU_FAULT_PLAN='{self.seed}' "
                f"(StormPlan(seed={self.seed}, "
                f"duration_s={self.duration_s}, "
                f"intensity={self.intensity}, kinds={self.kinds!r}))")


def storm_seed_from_env(default: int = 0) -> int:
    """The one-seed activation path: RAY_TPU_FAULT_PLAN may carry a
    bare integer (storm seed) or a full JSON plan (its ``seed`` field
    is reused), so one environment variable replays either kind of
    schedule."""
    raw = os.environ.get("RAY_TPU_FAULT_PLAN", "").strip()
    if not raw:
        return int(default)
    try:
        return int(raw)
    except ValueError:  # raycheck: disable=RC05 — not-an-int means "try the JSON-plan form next"; the fallthrough IS the handling
        pass
    try:
        return int(load_plan(raw).get("seed", default))
    except Exception:
        logger.debug("RAY_TPU_FAULT_PLAN is neither an integer seed "
                     "nor a plan; storm uses default seed %s", default)
        return int(default)
