"""Parent-side management of worker processes.

``WorkerProcess``     — one child (Popen) + framed pipe protocol.
``ProcessWorkerPool`` — leased pool for normal tasks (reference:
                        worker_pool.h:144 PopWorker/PushWorker; idle
                        workers are reused, dead ones replaced).
``ActorProcess``      — dedicated child owning a live actor instance
                        (the reference starts one worker process per
                        actor; calls bypass the raylet and go straight
                        to it, transport/direct_actor_transport).

With ``warm_size > 0`` the pool additionally keeps that many IDLE
pre-forked workers (reference: worker_pool.cc prestart /
num_initial_python_workers): ``create_actor_process`` leases one
instantly and specializes it in place by shipping ``actor_create``
over the already-open pipe — interpreter boot and imports were paid
before the lease. A background replenisher (ThreadRegistry-owned)
refills after every lease; an empty pool falls back to the cold fork.
On kill, a worker whose actor left no process-global residue returns
to the pool (``actor_reset``); a dirty or busy one is reaped.

Death detection: any pipe error while a task is in flight surfaces as
``WorkerCrashedError`` carrying the pid — the owner-side signal that
drives retries and actor restarts, like the reference's disconnect
handling in NodeManager::HandleUnexpectedWorkerFailure.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu.cluster import protocol
from ray_tpu.exceptions import WorkerCrashedError

logger = logging.getLogger(__name__)


class WorkerBusyError(Exception):
    """A non-blocking pipe call found an in-flight call holding the
    worker's lock (warm-pool return path only)."""


class WorkerProcess:
    """One OS worker process plus its control pipes.

    With ``log_callback`` set, the child's stderr (where its print()s and
    tracebacks land — stdout is the framed reply pipe) is captured and
    fed line-by-line to the callback, the seam the reference's log
    monitor tails worker logs through (python/ray/_private/log_monitor.py).
    """

    def __init__(self, shm_path: str = "", log_callback=None,
                 preimport: str = ""):
        from ray_tpu.cluster.child_env import sanitized_env

        self.shm_path = shm_path
        # workers never own the parent's accelerator and must not run
        # eager accelerator site hooks (see cluster/child_env.py); user
        # PYTHONPATH entries survive so their code imports in workers
        env = sanitized_env(pin_pythonpath=False)
        argv = [sys.executable, "-m", "ray_tpu.cluster.worker_main",
                "--shm", shm_path,
                "--protocol-version", str(protocol.PIPE_PROTOCOL_VERSION)]
        if preimport:
            argv += ["--preimport", preimport]
        self._proc = subprocess.Popen(
            argv,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE if log_callback else None,
            env=env,
            text=False,
        )
        if log_callback is not None:
            # raycheck: disable=RC09 — stderr drain lives exactly as long as the worker child process: it exits on pipe EOF when the child dies, so the process (not a registry) is its teardown
            threading.Thread(
                target=self._drain_stderr, args=(log_callback,),
                daemon=True, name=f"worker-log-{self._proc.pid}").start()
        self._lock = threading.Lock()
        self._shm = None
        if shm_path:
            try:
                from ray_tpu._native.shm_store import ShmStore

                self._shm = ShmStore.open(shm_path)
            except Exception:
                self.shm_path = ""
        self.dead = False

    def _drain_stderr(self, log_callback) -> None:
        pid = self._proc.pid
        try:
            for raw in iter(self._proc.stderr.readline, b""):
                try:
                    log_callback(pid, raw.decode("utf-8", "replace")
                                 .rstrip("\n"))
                except Exception as e:
                    # a log sink must never kill the drain
                    logger.debug("log callback for worker %d failed: "
                                 "%r", pid, e)
        except (ValueError, OSError) as e:
            # pipe closed on shutdown
            logger.debug("stderr drain for worker %d ended: %r", pid, e)

    @property
    def pid(self) -> int:
        return self._proc.pid

    def call(self, msg_type: str, payload: Dict[str, Any]) -> Any:
        """Send one request and block for its reply. Raises
        WorkerCrashedError if the process dies mid-call."""
        with self._lock:
            if self.dead:
                raise WorkerCrashedError(
                    f"worker process {self.pid} already dead")
            try:
                protocol.send(self._proc.stdin, (msg_type, payload),
                              self._shm)
                reply, body = protocol.recv(self._proc.stdout, self._shm)
            except (protocol.PipeClosedError, BrokenPipeError, OSError) as e:
                self.dead = True
                self._proc.poll()
                raise WorkerCrashedError(
                    f"worker process {self.pid} died during "
                    f"{msg_type} (exit={self._proc.returncode}): {e}"
                ) from None
        if reply == "ok":
            return body
        raise protocol.restore_exception(*body)

    def try_call(self, msg_type: str, payload: Dict[str, Any]) -> Any:
        """``call`` that refuses to wait for the pipe lock: raises
        ``WorkerBusyError`` when an in-flight call holds it. Used by the
        warm-pool return path — a worker still executing a method when
        its actor is killed must be SIGKILLed, not waited on."""
        if not self._lock.acquire(blocking=False):
            raise WorkerBusyError(
                f"worker process {self.pid} has a call in flight")
        try:
            if self.dead:
                raise WorkerCrashedError(
                    f"worker process {self.pid} already dead")
            try:
                protocol.send(self._proc.stdin, (msg_type, payload),
                              self._shm)
                reply, body = protocol.recv(self._proc.stdout, self._shm)
            except (protocol.PipeClosedError, BrokenPipeError, OSError) as e:
                self.dead = True
                self._proc.poll()
                raise WorkerCrashedError(
                    f"worker process {self.pid} died during "
                    f"{msg_type} (exit={self._proc.returncode}): {e}"
                ) from None
        finally:
            self._lock.release()
        if reply == "ok":
            return body
        raise protocol.restore_exception(*body)

    def ping(self) -> bool:
        try:
            return self.call("ping", {}) == self.pid
        except Exception:
            return False

    def alive(self) -> bool:
        return not self.dead and self._proc.poll() is None

    def terminate(self, timeout: float = 2.0) -> None:
        """``timeout=0`` skips the graceful shutdown message and
        SIGKILLs outright: on a host starved by a large worker fleet,
        waking each worker to read the shutdown frame costs seconds of
        scheduling latency per process — a 2000-actor teardown cannot
        afford it, and a pool-managed worker holds no state worth the
        drain."""
        self.dead = True
        if self._proc.poll() is not None:
            return
        # Never block on the call lock: an in-flight call holds it for
        # the task's whole duration, and terminating a busy worker (kill
        # of a looping actor, pool shutdown) must not hang behind it.
        if timeout > 0 and self._lock.acquire(blocking=False):
            try:
                protocol.send(self._proc.stdin, ("shutdown", {}), None)
            except Exception as e:
                # stdin already closed: the kill below still lands
                logger.debug("graceful shutdown of worker %d failed: "
                             "%r", self.pid, e)
            finally:
                self._lock.release()
            try:
                self._proc.wait(timeout=timeout)
                return
            except subprocess.TimeoutExpired as e:
                logger.debug("worker %d ignored shutdown; killing: %r",
                             self.pid, e)
        self._proc.kill()
        self._proc.wait()


class ProcessWorkerPool:
    """Fixed-size pool of leased worker processes for normal tasks,
    plus (``warm_size > 0``) a warm pool of pre-forked idle workers
    leased instantly to actors."""

    def __init__(self, size: int, shm_path: str = "", log_callback=None,
                 warm_size: int = 0, threads=None):
        self.size = max(1, size)
        self.shm_path = shm_path
        self.log_callback = log_callback
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # raycheck: disable=RC10 — holds at most `size` worker handles (the fixed pool population); nothing else ever enqueues here
        self._idle: deque[WorkerProcess] = deque()
        self._all: List[WorkerProcess] = []
        self._shutdown = False
        self._actor_procs: List["ActorProcess"] = []
        for _ in range(self.size):
            self._spawn_locked()
        # ---- warm actor-worker pool (worker_pool.cc prestart) ----
        self.warm_size = max(0, warm_size)
        self._warm_cv = threading.Condition()
        # raycheck: disable=RC10 — bounded by the explicit warm-pool caps: the replenisher stops at warm_size and _warm_return reaps beyond 2*warm_size
        self._warm: deque[WorkerProcess] = deque()
        self.num_warm_hits = 0
        self.num_warm_misses = 0
        self.num_warm_returned = 0
        self.num_warm_reaped = 0
        self.num_warm_specialize_crashes = 0
        if self.warm_size > 0:
            from ray_tpu._private.config import Config

            self._preimport = Config.instance().worker_pool_preimport
            if threads is None:
                from ray_tpu.cluster.threads import ThreadRegistry

                threads = self._own_threads = ThreadRegistry(
                    "process-pool")
            else:
                self._own_threads = None
            threads.spawn(self._replenish_loop, "worker-pool-replenish")
        else:
            self._preimport = ""
            self._own_threads = None

    def _spawn_locked(self) -> None:
        worker = WorkerProcess(self.shm_path,
                               log_callback=self.log_callback)
        self._all.append(worker)
        self._idle.append(worker)

    def _lease(self) -> WorkerProcess:
        with self._cv:
            while True:
                if self._shutdown:
                    raise RuntimeError("process pool is shut down")
                while self._idle:
                    worker = self._idle.popleft()
                    if worker.alive():
                        return worker
                    # died while idle: replace it
                    self._all.remove(worker)
                    self._spawn_locked()
                self._cv.wait()

    def _release(self, worker: WorkerProcess) -> None:
        with self._cv:
            if worker.dead or not worker.alive():
                if worker in self._all:
                    self._all.remove(worker)
                if not self._shutdown:
                    self._spawn_locked()
            else:
                self._idle.append(worker)
            self._cv.notify()

    # ---------------------------------------------------- warm actor pool
    def _replenish_loop(self) -> None:
        """Keep ``warm_size`` idle workers pre-forked. The fork happens
        OUTSIDE the condition hold — it takes worker-boot time, during
        which leases keep draining the pool without blocking."""
        while True:
            with self._warm_cv:
                while not self._shutdown and \
                        len(self._warm) >= self.warm_size:
                    self._warm_cv.wait(0.5)
                if self._shutdown:
                    return
            try:
                worker = WorkerProcess(self.shm_path,
                                       log_callback=self.log_callback,
                                       preimport=self._preimport)
            except Exception as e:  # noqa: BLE001 — e.g. fork EAGAIN
                logger.warning("warm worker fork failed: %r", e)
                time.sleep(0.5)
                continue
            with self._warm_cv:
                if self._shutdown:
                    stale = worker
                else:
                    self._warm.append(worker)
                    self._warm_cv.notify_all()
                    stale = None
                self._gauge_locked()
            if stale is not None:
                stale.terminate()
                return

    def _gauge_locked(self) -> None:
        from ray_tpu.observability.metrics import worker_pool_size

        worker_pool_size.set(len(self._warm))

    def _warm_lease(self) -> Optional[WorkerProcess]:
        """Pop a live pre-forked worker, or None (cold-fork fallback).
        Counts the hit/miss either way."""
        from ray_tpu.observability.metrics import (
            worker_pool_warm_hits,
            worker_pool_warm_misses,
        )

        reap = []
        try:
            with self._warm_cv:
                while self._warm:
                    worker = self._warm.popleft()
                    self._warm_cv.notify_all()  # wake the replenisher
                    if worker.alive():
                        self.num_warm_hits += 1
                        worker_pool_warm_hits.inc()
                        return worker
                    reap.append(worker)  # died while idle
                self.num_warm_misses += 1
                worker_pool_warm_misses.inc()
                return None
        finally:
            with self._warm_cv:
                self._gauge_locked()
            for w in reap:
                w.terminate()

    def _warm_return(self, proc: "ActorProcess") -> bool:
        """Return a killed actor's worker to the warm pool if it is
        demonstrably clean; else reap it. True = worker kept alive in
        the pool (the caller must NOT terminate it)."""
        worker = proc.worker
        clean = (not self._shutdown and not proc.had_runtime_env
                 and worker.alive())
        if clean:
            with self._warm_cv:
                # capacity pre-check BEFORE paying the actor_reset
                # round trip: during a fleet teardown most workers are
                # headed for the reaper anyway, and waking each one to
                # reset it first costs seconds apiece on a starved host
                clean = len(self._warm) < 2 * self.warm_size
        if clean:
            try:
                # non-blocking: a worker mid-method (busy kill) must be
                # SIGKILLed, matching the dedicated-process semantics
                reply = worker.try_call("actor_reset", {})
                clean = bool(reply and reply.get("clean"))
            except Exception as e:  # noqa: BLE001 — busy/crashed/errored
                logger.debug("actor_reset of worker %d failed: %r",
                             worker.pid, e)
                clean = False
        if clean:
            with self._warm_cv:
                # accept returns past warm_size (they pre-empt the next
                # replenisher fork) but never hoard beyond 2x
                if not self._shutdown and \
                        len(self._warm) < 2 * self.warm_size:
                    self._warm.append(worker)
                    self.num_warm_returned += 1
                    self._warm_cv.notify_all()
                    self._gauge_locked()
                    return True
        with self._warm_cv:
            self.num_warm_reaped += 1
        return False

    def run(self, func, args: tuple, kwargs: dict,
            runtime_env=None, result_key: Optional[bytes] = None) -> Any:
        """``result_key`` (a 20-byte shm-store key) asks the worker to
        write a large result straight into the node's shm segment under
        that key and reply with a protocol.StoredResult marker — the
        caller then adopts the segment entry without the payload ever
        crossing the pipe."""
        worker = self._lease()
        try:
            return worker.call("task", {
                "func": func, "args": args, "kwargs": kwargs,
                "runtime_env": runtime_env,
                "result_key": result_key,
            })
        finally:
            self._release(worker)

    def run_batch(self, items: List[dict]) -> List[tuple]:
        """Batched ``run`` (dispatch fast lane): lease ONE worker and
        ship all ``items`` — each the same payload dict ``run`` sends
        (func/args/kwargs/runtime_env/result_key) — as a single
        ``task_batch`` pipe frame; the worker executes them serially
        and the N results come back in one reply frame. Returns one
        ``("ok", value)`` or ``("err", exception)`` row per item, in
        order: a row's user exception never fails its siblings. Only a
        worker death mid-batch raises (WorkerCrashedError), failing
        the whole batch for the caller to fan out."""
        worker = self._lease()
        try:
            rows = worker.call("task_batch", {"items": items})
        finally:
            self._release(worker)
        return [(status, body) if status == "ok"
                else (status, protocol.restore_exception(*body))
                for status, body in rows]

    def create_actor_process(self, cls, args: tuple, kwargs: dict,
                             runtime_env=None) -> "ProcessActorProxy":
        proc = None
        if self.warm_size > 0:
            worker = self._warm_lease()
            if worker is not None:
                try:
                    proc = ActorProcess(cls, args, kwargs, runtime_env,
                                        worker=worker, pool=self)
                except WorkerCrashedError:
                    # the leased worker died between the liveness check
                    # and specialization (its dead pipe is already
                    # reaped by ActorProcess): cold-fork below without
                    # surfacing an error — the caller never sees the
                    # burned lease. User __init__ errors re-raise — a
                    # fresh fork cannot fix those.
                    from ray_tpu.observability.metrics import (
                        warm_specialize_crash_fallbacks,
                    )

                    with self._warm_cv:
                        self.num_warm_specialize_crashes += 1
                        self.num_warm_reaped += 1
                    warm_specialize_crash_fallbacks.inc()
                    logger.info(
                        "warm worker %d died during in-place "
                        "specialization; reaped, cold-forking instead",
                        worker.pid)
                    proc = None
        if proc is None:
            proc = ActorProcess(cls, args, kwargs, runtime_env,
                                shm_path=self.shm_path,
                                log_callback=self.log_callback,
                                pool=self if self.warm_size > 0 else None)
        with self._lock:
            # prune incarnations whose processes are gone (killed or
            # crash-looped actors; a pool-returned worker outlives its
            # actor, so `gone` is checked too) so the registry doesn't
            # grow unboundedly
            self._actor_procs = [p for p in self._actor_procs
                                 if p.worker.alive() and not p.gone]
            self._actor_procs.append(proc)
        return ProcessActorProxy(proc)

    def pids(self) -> List[int]:
        with self._lock:
            return [w.pid for w in self._all if w.alive()]

    def stats(self) -> dict:
        with self._lock:
            out = {
                "size": self.size,
                "alive": sum(1 for w in self._all if w.alive()),
                "idle": len(self._idle),
                "actors": sum(1 for p in self._actor_procs
                              if p.worker.alive() and not p.gone),
            }
        with self._warm_cv:
            out.update({
                "warm_size": self.warm_size,
                "warm_idle": len(self._warm),
                "warm_hits": self.num_warm_hits,
                "warm_misses": self.num_warm_misses,
                "warm_returned": self.num_warm_returned,
                "warm_reaped": self.num_warm_reaped,
                "warm_specialize_crashes":
                    self.num_warm_specialize_crashes,
            })
        return out

    def shutdown(self) -> None:
        with self._cv:
            self._shutdown = True
            workers = list(self._all)
            actors = list(self._actor_procs)
            self._all.clear()
            self._idle.clear()
            self._cv.notify_all()
        with self._warm_cv:
            warm = list(self._warm)
            self._warm.clear()
            self._warm_cv.notify_all()
        for w in warm:
            w.terminate()
        for w in workers:
            w.terminate()
        for a in actors:
            a.terminate()
        if self._own_threads is not None:
            self._own_threads.join_all(timeout=2.0)


class ActorProcess:
    """A worker process holding one live actor instance — either a
    freshly forked dedicated child (classic path) or a warm worker
    leased from the pool and specialized in place (``worker=``)."""

    def __init__(self, cls, args: tuple, kwargs: dict, runtime_env=None,
                 shm_path: str = "", log_callback=None,
                 worker: Optional[WorkerProcess] = None, pool=None):
        self.pool = pool
        self.had_runtime_env = runtime_env is not None
        self.warm = worker is not None
        self.gone = False  # terminated (worker may live on in the pool)
        self.worker = worker if worker is not None else WorkerProcess(
            shm_path, log_callback=log_callback)
        try:
            self.worker.call("actor_create", {
                "cls": cls, "args": args, "kwargs": kwargs,
                "runtime_env": runtime_env,
            })
        except BaseException:
            # covers user __init__ errors too: the worker may hold a
            # half-entered runtime_env, so it never returns to the pool
            self.worker.terminate()
            raise

    @property
    def pid(self) -> int:
        return self.worker.pid

    def call_method(self, method: str, args: tuple, kwargs: dict) -> Any:
        return self.worker.call("actor_call", {
            "method": method, "args": args, "kwargs": kwargs,
        })

    def terminate(self) -> None:
        self.gone = True
        if self.pool is not None:
            if self.pool._warm_return(self):
                return  # worker reset clean and rejoined the warm pool
            # pool-managed reap: hard-kill. The graceful 2 s wait per
            # worker — not the RPC chain — is what made a 2000-actor
            # teardown take 204 s on a starved host (SCALE_r05), and a
            # declined return means the worker's state is disposable.
            self.worker.terminate(timeout=0.0)
            return
        self.worker.terminate()  # dedicated-process (pool-off) path


class ProcessActorProxy:
    """Stands in for the actor instance inside the parent's ActorExecutor:
    attribute access returns a callable that pushes the method call to the
    dedicated process. Mirrors how the reference's ActorHandle proxies
    method descriptors to the remote worker."""

    def __init__(self, proc: ActorProcess):
        # deliberately obscure attribute name: anything the proxy defines
        # shadows a same-named user actor method (getattr resolution)
        object.__setattr__(self, "_ray_tpu_actor_proc", proc)

    def __getattr__(self, name: str):
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        proc = object.__getattribute__(self, "_ray_tpu_actor_proc")

        def _call(*args, **kwargs):
            return proc.call_method(name, args, kwargs)

        _call.__name__ = name
        return _call

    def __ray_proxy_pid__(self) -> int:
        return object.__getattribute__(self, "_ray_tpu_actor_proc").pid

    def __ray_on_kill__(self) -> None:
        object.__getattribute__(self, "_ray_tpu_actor_proc").terminate()
