"""Parent-side management of worker processes.

``WorkerProcess``     — one child (Popen) + framed pipe protocol.
``ProcessWorkerPool`` — leased pool for normal tasks (reference:
                        worker_pool.h:144 PopWorker/PushWorker; idle
                        workers are reused, dead ones replaced).
``ActorProcess``      — dedicated child owning a live actor instance
                        (the reference starts one worker process per
                        actor; calls bypass the raylet and go straight
                        to it, transport/direct_actor_transport).

Death detection: any pipe error while a task is in flight surfaces as
``WorkerCrashedError`` carrying the pid — the owner-side signal that
drives retries and actor restarts, like the reference's disconnect
handling in NodeManager::HandleUnexpectedWorkerFailure.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu.cluster import protocol
from ray_tpu.exceptions import WorkerCrashedError

logger = logging.getLogger(__name__)


class WorkerProcess:
    """One OS worker process plus its control pipes.

    With ``log_callback`` set, the child's stderr (where its print()s and
    tracebacks land — stdout is the framed reply pipe) is captured and
    fed line-by-line to the callback, the seam the reference's log
    monitor tails worker logs through (python/ray/_private/log_monitor.py).
    """

    def __init__(self, shm_path: str = "", log_callback=None):
        from ray_tpu.cluster.child_env import sanitized_env

        self.shm_path = shm_path
        # workers never own the parent's accelerator and must not run
        # eager accelerator site hooks (see cluster/child_env.py); user
        # PYTHONPATH entries survive so their code imports in workers
        env = sanitized_env(pin_pythonpath=False)
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.cluster.worker_main",
             "--shm", shm_path,
             "--protocol-version", str(protocol.PIPE_PROTOCOL_VERSION)],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE if log_callback else None,
            env=env,
            text=False,
        )
        if log_callback is not None:
            # raycheck: disable=RC09 — stderr drain lives exactly as long as the worker child process: it exits on pipe EOF when the child dies, so the process (not a registry) is its teardown
            threading.Thread(
                target=self._drain_stderr, args=(log_callback,),
                daemon=True, name=f"worker-log-{self._proc.pid}").start()
        self._lock = threading.Lock()
        self._shm = None
        if shm_path:
            try:
                from ray_tpu._native.shm_store import ShmStore

                self._shm = ShmStore.open(shm_path)
            except Exception:
                self.shm_path = ""
        self.dead = False

    def _drain_stderr(self, log_callback) -> None:
        pid = self._proc.pid
        try:
            for raw in iter(self._proc.stderr.readline, b""):
                try:
                    log_callback(pid, raw.decode("utf-8", "replace")
                                 .rstrip("\n"))
                except Exception as e:
                    # a log sink must never kill the drain
                    logger.debug("log callback for worker %d failed: "
                                 "%r", pid, e)
        except (ValueError, OSError) as e:
            # pipe closed on shutdown
            logger.debug("stderr drain for worker %d ended: %r", pid, e)

    @property
    def pid(self) -> int:
        return self._proc.pid

    def call(self, msg_type: str, payload: Dict[str, Any]) -> Any:
        """Send one request and block for its reply. Raises
        WorkerCrashedError if the process dies mid-call."""
        with self._lock:
            if self.dead:
                raise WorkerCrashedError(
                    f"worker process {self.pid} already dead")
            try:
                protocol.send(self._proc.stdin, (msg_type, payload),
                              self._shm)
                reply, body = protocol.recv(self._proc.stdout, self._shm)
            except (protocol.PipeClosedError, BrokenPipeError, OSError) as e:
                self.dead = True
                self._proc.poll()
                raise WorkerCrashedError(
                    f"worker process {self.pid} died during "
                    f"{msg_type} (exit={self._proc.returncode}): {e}"
                ) from None
        if reply == "ok":
            return body
        raise protocol.restore_exception(*body)

    def ping(self) -> bool:
        try:
            return self.call("ping", {}) == self.pid
        except Exception:
            return False

    def alive(self) -> bool:
        return not self.dead and self._proc.poll() is None

    def terminate(self, timeout: float = 2.0) -> None:
        self.dead = True
        if self._proc.poll() is not None:
            return
        # Never block on the call lock: an in-flight call holds it for
        # the task's whole duration, and terminating a busy worker (kill
        # of a looping actor, pool shutdown) must not hang behind it.
        if self._lock.acquire(blocking=False):
            try:
                protocol.send(self._proc.stdin, ("shutdown", {}), None)
            except Exception as e:
                # stdin already closed: the kill below still lands
                logger.debug("graceful shutdown of worker %d failed: "
                             "%r", self.pid, e)
            finally:
                self._lock.release()
            try:
                self._proc.wait(timeout=timeout)
                return
            except subprocess.TimeoutExpired as e:
                logger.debug("worker %d ignored shutdown; killing: %r",
                             self.pid, e)
        self._proc.kill()
        self._proc.wait()


class ProcessWorkerPool:
    """Fixed-size pool of leased worker processes for normal tasks."""

    def __init__(self, size: int, shm_path: str = "", log_callback=None):
        self.size = max(1, size)
        self.shm_path = shm_path
        self.log_callback = log_callback
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # raycheck: disable=RC10 — holds at most `size` worker handles (the fixed pool population); nothing else ever enqueues here
        self._idle: deque[WorkerProcess] = deque()
        self._all: List[WorkerProcess] = []
        self._shutdown = False
        self._actor_procs: List["ActorProcess"] = []
        for _ in range(self.size):
            self._spawn_locked()

    def _spawn_locked(self) -> None:
        worker = WorkerProcess(self.shm_path,
                               log_callback=self.log_callback)
        self._all.append(worker)
        self._idle.append(worker)

    def _lease(self) -> WorkerProcess:
        with self._cv:
            while True:
                if self._shutdown:
                    raise RuntimeError("process pool is shut down")
                while self._idle:
                    worker = self._idle.popleft()
                    if worker.alive():
                        return worker
                    # died while idle: replace it
                    self._all.remove(worker)
                    self._spawn_locked()
                self._cv.wait()

    def _release(self, worker: WorkerProcess) -> None:
        with self._cv:
            if worker.dead or not worker.alive():
                if worker in self._all:
                    self._all.remove(worker)
                if not self._shutdown:
                    self._spawn_locked()
            else:
                self._idle.append(worker)
            self._cv.notify()

    def run(self, func, args: tuple, kwargs: dict,
            runtime_env=None, result_key: Optional[bytes] = None) -> Any:
        """``result_key`` (a 20-byte shm-store key) asks the worker to
        write a large result straight into the node's shm segment under
        that key and reply with a protocol.StoredResult marker — the
        caller then adopts the segment entry without the payload ever
        crossing the pipe."""
        worker = self._lease()
        try:
            return worker.call("task", {
                "func": func, "args": args, "kwargs": kwargs,
                "runtime_env": runtime_env,
                "result_key": result_key,
            })
        finally:
            self._release(worker)

    def create_actor_process(self, cls, args: tuple, kwargs: dict,
                             runtime_env=None) -> "ProcessActorProxy":
        proc = ActorProcess(cls, args, kwargs, runtime_env,
                            shm_path=self.shm_path,
                            log_callback=self.log_callback)
        with self._lock:
            # prune incarnations whose processes are gone (killed or
            # crash-looped actors) so the registry doesn't grow unboundedly
            self._actor_procs = [p for p in self._actor_procs
                                 if p.worker.alive()]
            self._actor_procs.append(proc)
        return ProcessActorProxy(proc)

    def pids(self) -> List[int]:
        with self._lock:
            return [w.pid for w in self._all if w.alive()]

    def stats(self) -> dict:
        with self._lock:
            return {
                "size": self.size,
                "alive": sum(1 for w in self._all if w.alive()),
                "idle": len(self._idle),
                "actors": sum(1 for p in self._actor_procs
                              if p.worker.alive()),
            }

    def shutdown(self) -> None:
        with self._cv:
            self._shutdown = True
            workers = list(self._all)
            actors = list(self._actor_procs)
            self._all.clear()
            self._idle.clear()
            self._cv.notify_all()
        for w in workers:
            w.terminate()
        for a in actors:
            a.terminate()


class ActorProcess:
    """A dedicated worker process holding one live actor instance."""

    def __init__(self, cls, args: tuple, kwargs: dict, runtime_env=None,
                 shm_path: str = "", log_callback=None):
        self.worker = WorkerProcess(shm_path, log_callback=log_callback)
        try:
            self.worker.call("actor_create", {
                "cls": cls, "args": args, "kwargs": kwargs,
                "runtime_env": runtime_env,
            })
        except BaseException:
            self.worker.terminate()
            raise

    @property
    def pid(self) -> int:
        return self.worker.pid

    def call_method(self, method: str, args: tuple, kwargs: dict) -> Any:
        return self.worker.call("actor_call", {
            "method": method, "args": args, "kwargs": kwargs,
        })

    def terminate(self) -> None:
        self.worker.terminate()


class ProcessActorProxy:
    """Stands in for the actor instance inside the parent's ActorExecutor:
    attribute access returns a callable that pushes the method call to the
    dedicated process. Mirrors how the reference's ActorHandle proxies
    method descriptors to the remote worker."""

    def __init__(self, proc: ActorProcess):
        # deliberately obscure attribute name: anything the proxy defines
        # shadows a same-named user actor method (getattr resolution)
        object.__setattr__(self, "_ray_tpu_actor_proc", proc)

    def __getattr__(self, name: str):
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        proc = object.__getattribute__(self, "_ray_tpu_actor_proc")

        def _call(*args, **kwargs):
            return proc.call_method(name, args, kwargs)

        _call.__name__ = name
        return _call

    def __ray_proxy_pid__(self) -> int:
        return object.__getattribute__(self, "_ray_tpu_actor_proc").pid

    def __ray_on_kill__(self) -> None:
        object.__getattribute__(self, "_ray_tpu_actor_proc").terminate()
