"""Integrity plane — end-to-end object checksums at every
data-movement seam.

The fault plane (cluster/fault_plane.py) covers *loss* — dropped,
delayed, duplicated, truncated frames — and the overload plane covers
*load*. Neither covers a payload that arrives **wrong**: a flipped bit
in a push chunk, a spill file half-written by a SIGKILLed raylet, or a
shm segment scribbled by a dying worker flows through every transfer
seam unverified and becomes a silently-wrong ``ray.get()`` result.
Production fleets see exactly this class of silent data corruption at
scale (Hochschild et al., "Cores that don't count", HotOS '21; Dixit
et al., "Silent Data Corruptions at Scale", '21). The reference's
plasma store seals objects immutably and its transfer plane moves
sealed chunks; this module adds the missing end-to-end check.

Design: ONE digest per object, computed at creation (``ByteStore.put``
/ the worker's shm result write / spill time in ``MemoryStore``) and
carried alongside the payload across every boundary:

- entry metadata (``_Entry.crc`` / ``StoredObject.crc``),
- the push wire schema (optional ``crc`` on ``push_begin`` /
  ``push_chunk`` / ``push_offer``, cluster/schema.py),
- the chunked pull stream's header frame (``get_object``),
- a spill-file header (``SPILL_MAGIC`` + flags + crc, written by both
  store tiers),
- a shm segment trailer (``TRAILER_MAGIC`` + crc appended after the
  payload inside the segment entry, invisible to readers that slice
  the logical size).

Verification fires where bytes cross a trust boundary: push-receive
assembly, pull completion, spill restore, ``adopt_shm`` and orphan
spill-file reclaim, and (knob-gated, default off) at ``ray.get``
deserialization. On mismatch the holder raises the typed
:class:`~ray_tpu.exceptions.ObjectCorruptedError`, discards the
corrupt replica, and the normal recovery machinery — re-pull from
another holder, push retry, lineage reconstruction — delivers the
correct value or a typed error. Never garbage.

The digest is CRC32C (Castagnoli) via the hardware-accelerated
``google_crc32c`` C extension when present (~20 GiB/s with SSE4.2 /
ARMv8 CRC instructions), falling back to zlib.crc32 (~1 GiB/s
slice-by-8; hashlib.blake2b-8 measured 0.68 GiB/s, adler32 is faster
but weak on short payloads). Either is strong enough for fault
detection (this is an integrity check against bit rot and torn
writes, not an authenticity check against an adversary). The backend
is chosen once at import and is identical across every process of an
incarnation (driver, raylets, pipe workers share the interpreter and
site-packages), so digests agree at every seam; only orphan spill
files written by an incarnation with a DIFFERENT backend fail their
header check at reclaim — and are dropped, which is the designed
response to any unverifiable spill. ``bench.py`` records the cost as
``integrity_overhead_pct`` on the broadcast and scheduler rows and
``integrity_store_put_get_overhead_pct`` at the store layer.

Knobs (``_private/config.py``): ``integrity_enabled`` (master switch,
default on) and ``integrity_verify_on_get`` (the paranoid end-to-end
re-check at deserialization, default off — every transfer seam already
verified the bytes it moved).
"""

from __future__ import annotations

import struct
import zlib
from typing import Optional, Tuple

# ---------------------------------------------------------------- digest

try:
    # hardware CRC32C: the C extension only — the package's pure-python
    # fallback is slower than zlib and would invert the trade
    from google_crc32c import extend as _crc32c_extend
    from google_crc32c import implementation as _crc32c_impl
    from google_crc32c import value as _crc32c_value

    if _crc32c_impl != "c":
        _crc32c_value = None
        _crc32c_extend = None
except ImportError:
    _crc32c_value = None
    _crc32c_extend = None

CHECKSUM_IMPL = "crc32c" if _crc32c_value is not None else "crc32"


def _crc_buffer(data):
    """Adapt a bytes-like object for the crc32c C extension, which only
    accepts read-only buffers (bytes) — or ndarrays, whose buffer
    export it happens to take. Wrapping writable buffers (bytearray,
    shm memoryviews) in a zero-copy ndarray view keeps the data-plane
    seams digesting in place instead of paying a copy per chunk."""
    if type(data) is bytes:
        return data
    try:
        import numpy as np

        return np.frombuffer(data, dtype=np.uint8)
    except (ImportError, ValueError, BufferError):
        return bytes(data)


def checksum(data) -> int:
    """Digest of a bytes-like object (bytes/bytearray/contiguous
    memoryview). The one digest the whole plane carries — always a
    uint32, so the trailer/spill-header formats are backend-agnostic.
    Non-bytes buffers ride a zero-copy ndarray view into the C
    extension (see ``_crc_buffer``), so shm slices digest in place."""
    if _crc32c_value is not None:
        return _crc32c_extend(0, _crc_buffer(data))
    return zlib.crc32(data) & 0xFFFFFFFF


def checksum_update(state: int, data) -> int:
    """Extend a running digest with the next chunk of a stream; start
    from ``state=0`` and the final state equals ``checksum(whole)``.
    This is the fused-crc primitive: the chunk-tree receive path calls
    it on each slice right after ``recv_into`` lands it in the
    segment, while the bytes are still cache-hot, so the whole-object
    digest costs one warm pass fused into the copy instead of a second
    cold traversal at assembly (the PR 11 store-put fusion, extended
    to the streaming seams)."""
    if _crc32c_value is not None:
        return _crc32c_extend(state, _crc_buffer(data))
    return zlib.crc32(data, state) & 0xFFFFFFFF


def enabled() -> bool:
    from ray_tpu._private.config import Config

    return Config.instance().integrity_enabled


def verify_on_get() -> bool:
    from ray_tpu._private.config import Config

    cfg = Config.instance()
    return cfg.integrity_enabled and cfg.integrity_verify_on_get


def verify_shm_reads() -> bool:
    """Whether same-host shm fast-path reads re-verify their bytes.
    Default on since the data-plane pipeline — see the
    ``integrity_verify_shm_reads`` knob: segment adoption verifies by
    an O(1) trailer-digest compare and the copying paths fuse a
    hardware crc32c into the copy pass, so the verify that used to
    rival the transfer cost itself is now within noise. The trailer
    always rides the segment, so the knob toggles with no format
    change."""
    from ray_tpu._private.config import Config

    cfg = Config.instance()
    return cfg.integrity_enabled and cfg.integrity_verify_shm_reads


def record_corruption(seam: str, discarded: bool = True) -> None:
    """Count a detected corruption (and, usually, the discarded
    replica) in the Prometheus registry."""
    from ray_tpu.observability.metrics import (
        corrupt_replicas_discarded,
        objects_corruption_detected,
    )

    objects_corruption_detected.inc(tags={"seam": seam})
    if discarded:
        corrupt_replicas_discarded.inc()


def verify(data, crc: Optional[int], seam: str,
           object_id: bytes = b"") -> None:
    """Verify ``data`` against ``crc``; raises
    :class:`~ray_tpu.exceptions.ObjectCorruptedError` on mismatch
    (after counting it). No-op when the plane is off or the writer
    recorded no digest (``crc is None``)."""
    if crc is None or not enabled():
        return
    actual = checksum(data)
    if actual != crc:
        from ray_tpu.exceptions import ObjectCorruptedError

        record_corruption(seam)
        raise ObjectCorruptedError(
            object_id.hex() if object_id else "", seam,
            f"object {object_id.hex()[:16] or '?'} failed checksum "
            f"verification at seam {seam!r} "
            f"(expected {crc:#010x}, got {actual:#010x}); "
            f"corrupt replica discarded")
    from ray_tpu.observability.metrics import integrity_bytes_verified

    integrity_bytes_verified.inc(len(data))


def checksum_value(value) -> Optional[int]:
    """Digest of a buffer-typed in-process value (bytes, bytearray,
    contiguous ndarray, ...), or None for values with no stable byte
    representation — the in-process store holds live objects by
    reference, so only buffer values can carry a put-time digest
    without a serialization pass."""
    if isinstance(value, (bytes, bytearray)):
        return checksum(value)
    try:
        mv = memoryview(value)
    except TypeError:
        return None
    try:
        if not mv.contiguous:
            return None
        return checksum(mv.cast("B"))
    finally:
        mv.release()


# ----------------------------------------------------- spill-file header
# Layout: 4-byte magic | 1 flag byte (bit0 is_error, bit1 has_crc) |
# 4-byte big-endian crc32 | payload. Both store tiers write it; restore
# and orphan-reclaim verify it. (The pre-integrity layout was a single
# flag byte; spill files never outlive the code that wrote them except
# through the explicit orphan-reclaim path, which requires the header.)

SPILL_MAGIC = b"RTIC"
_SPILL = struct.Struct(">4sBI")
SPILL_HEADER_SIZE = _SPILL.size
_F_IS_ERROR = 0x01
_F_HAS_CRC = 0x02


def pack_spill_header(is_error: bool, crc: Optional[int]) -> bytes:
    flags = (_F_IS_ERROR if is_error else 0) | (
        _F_HAS_CRC if crc is not None else 0)
    return _SPILL.pack(SPILL_MAGIC, flags, crc or 0)


def parse_spill(raw) -> Tuple[bool, memoryview, Optional[int]]:
    """(is_error, payload_view, crc_or_None) from a spill file's bytes.
    Raises ValueError for files too short / wrong magic (a torn header
    IS corruption — the caller treats it like a failed digest)."""
    view = memoryview(raw)
    if len(view) < SPILL_HEADER_SIZE:
        raise ValueError("spill file shorter than its header")
    magic, flags, crc = _SPILL.unpack(bytes(view[:SPILL_HEADER_SIZE]))
    if magic != SPILL_MAGIC:
        raise ValueError(f"bad spill magic {magic!r}")
    has_crc = bool(flags & _F_HAS_CRC)
    return (bool(flags & _F_IS_ERROR), view[SPILL_HEADER_SIZE:],
            crc if has_crc else None)


# ----------------------------------------------------- shm entry trailer
# A writer that creates a shm entry with integrity on allocates
# logical_size + TRAILER_SIZE and appends magic+crc after the payload.
# Readers that know the logical size slice it off (and can verify);
# readers that don't (loads_flat) ignore trailing bytes by design.

TRAILER_MAGIC = b"RTIC"
_TRAILER = struct.Struct(">4sI")
TRAILER_SIZE = _TRAILER.size


def pack_trailer(crc: int) -> bytes:
    return _TRAILER.pack(TRAILER_MAGIC, crc)


def split_shm(buf, logical_size: int):
    """Interpret a pinned shm entry buffer of a ``logical_size``-byte
    object: returns ``(payload_view, crc_or_None)``, or ``(None,
    None)`` when the entry's length matches neither the bare nor the
    trailer-bearing layout (a stale or foreign entry)."""
    n = len(buf)
    if n == logical_size:
        return memoryview(buf)[:logical_size], None
    if n == logical_size + TRAILER_SIZE:
        magic, crc = _TRAILER.unpack(bytes(buf[logical_size:]))
        if magic == TRAILER_MAGIC:
            return memoryview(buf)[:logical_size], crc
    return None, None


def snapshot() -> dict:
    """This process's integrity counters — rides raylet heartbeats into
    ``cluster_view`` and prints in ``cli.py status``."""
    from ray_tpu.observability.metrics import get_metric

    def total(name: str) -> float:
        m = get_metric(name)
        return sum(m.series().values()) if m is not None else 0.0

    return {
        "corruption_detected": total("ray_tpu_objects_corruption_detected"),
        "corrupt_replicas_discarded": total(
            "ray_tpu_corrupt_replicas_discarded"),
        "bytes_verified": total("ray_tpu_integrity_bytes_verified"),
    }
