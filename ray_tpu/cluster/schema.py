"""Typed message schemas for the wire (reference: src/ray/protobuf/ —
20 .proto files give every cross-process message a schema; processes
reject what they cannot parse instead of guessing).

This build's wire bodies are pickled, so the schema layer is
dataclass-generated rather than IDL-compiled: each message type declares
its fields and types once, `validate()` checks an incoming kwargs dict
against them at dispatch — missing required fields and type mismatches
are rejected, unknown fields are dropped (proto3 posture), without
adding a codegen step to a pickle transport.

## Evolution rules (documented contract)

- **Adding an optional field (with a default) is backward-compatible in
  BOTH directions**: old senders omit it and `validate` fills the
  default; new senders include it and an old receiver DROPS the unknown
  field (proto3's unknown-field tolerance) — without the drop, a
  rolling upgrade inside one PROTOCOL_VERSION would wedge new->old
  calls. Dropped fields are counted in `validate.num_dropped` for
  observability.
- Removing a field, changing a field's type, or adding a REQUIRED field
  is breaking: bump `rpc.PROTOCOL_VERSION` so old peers are refused at
  the handshake instead of failing mid-call.

### Worked example (a real evolution in this repo)

`put_object` originally carried (object_id, payload, is_error,
register). The push/replica work added `primary: bool = True` — an
optional field with a default, so round-3-era senders that omit it
still validate and get the old semantics. Had `primary` been required,
the change would have needed a PROTOCOL_VERSION bump. The test suite
pins this example (tests/test_wire_protocol.py).
"""

from __future__ import annotations

from dataclasses import MISSING, dataclass, fields
from typing import Dict, Optional, Type


class SchemaError(TypeError):
    """An incoming message does not match its declared schema."""


_REGISTRY: Dict[str, Type] = {}


def message(method: str):
    """Class decorator registering a dataclass as METHOD's schema."""
    def wrap(cls):
        cls = dataclass(cls)
        _REGISTRY[method] = cls
        return cls
    return wrap


def schema_for(method: str) -> Optional[Type]:
    return _REGISTRY.get(method)


def validate(method: str, kwargs: dict) -> dict:
    """Check ``kwargs`` against METHOD's schema: unknown fields are
    DROPPED (proto3 unknown-field tolerance — a newer same-version peer
    may send optional fields this build predates), missing optional
    fields get their defaults, missing required fields and wrong types
    raise SchemaError. Methods without a registered schema pass through
    unchanged — a posture kept for test fixtures and plugins; every
    method the servers actually register declares a schema here, and
    raycheck RC07 fails the tree when one is missing."""
    cls = _REGISTRY.get(method)
    if cls is None:
        return kwargs
    declared = {f.name: f for f in fields(cls)}
    unknown = set(kwargs) - set(declared)
    out = {k: v for k, v in kwargs.items() if k in declared}
    if unknown:
        validate.num_dropped += len(unknown)
    for name, f in declared.items():
        if name not in out:
            if f.default is not MISSING:
                out[name] = f.default
            elif f.default_factory is not MISSING:  # type: ignore[misc]
                out[name] = f.default_factory()  # type: ignore[misc]
            else:
                raise SchemaError(f"{method}: missing required "
                                  f"field {name!r}")
            continue
        expected = _runtime_type(f.type)
        if expected is not None and out[name] is not None \
                and not isinstance(out[name], expected):
            raise SchemaError(
                f"{method}: field {name!r} expects "
                f"{f.type}, got {type(out[name]).__name__}")
    return out


validate.num_dropped = 0  # dropped unknown fields (rolling upgrades)


def _runtime_type(annotation):
    """Best-effort annotation -> isinstance() target. Returns None for
    annotations we can't check structurally (Any, unions, generics'
    parameters are not enforced beyond the origin type)."""
    mapping = {
        # any buffer type is wire-equivalent to bytes (dumps_flat
        # returns bytearray; chunked reads hand out memoryviews)
        "bytes": (bytes, bytearray, memoryview),
        "str": str, "bool": bool, "float": (int, float),
        "int": int, "dict": dict, "list": list, "tuple": tuple,
    }
    if isinstance(annotation, str):
        base = annotation.split("[")[0].strip()
        if base.startswith("Optional"):
            inner = annotation[annotation.index("[") + 1:-1]
            return _runtime_type(inner.split("[")[0].strip())
        if base in ("Dict", "dict"):
            return dict
        if base in ("List", "list"):
            return list
        return mapping.get(base)
    return None


# ----------------------------------------------------------------------
# Core data-plane message schemas (the highest-traffic, most
# version-sensitive messages; control-plane methods join incrementally).
# ----------------------------------------------------------------------

@message("put_object")
class PutObject:
    object_id: bytes
    payload: bytes
    is_error: bool = False
    register: bool = True
    # EVOLUTION EXAMPLE: added after v0 as optional-with-default (see
    # module docstring) — replica pushes mark copies non-primary
    primary: bool = True


@message("get_object_info")
class GetObjectInfo:
    object_id: bytes


@message("push_begin")
class PushBegin:
    object_id: bytes
    size: int
    is_error: bool = False
    # integrity plane: whole-object crc32 the receiver verifies at
    # assembly (optional-with-default per the evolution rules — an
    # integrity-disabled sender omits it and the receiver skips the
    # check)
    crc: "Optional[int]" = None
    # data-plane pipeline (optional-with-default, evolution rules): the
    # receiver's subtree of the broadcast chunk tree — a list of
    # [address, subtree] pairs it cut-through forwards each verified
    # chunk to. Pre-pipeline receivers drop the field and the tree
    # degrades to a direct push (driver re-pull covers the subtree).
    downstream: "Optional[list]" = None
    # Sender's chunk size for this transfer, so the receiver can size
    # coverage accounting and forward frames identically down the tree.
    chunk_bytes: "Optional[int]" = None
    # Chunk-tree failover (optional-with-default, evolution rules): set
    # by a re-rooted parent re-offering the stream after the receiver's
    # previous feeder died mid-tree. A receiver with
    # chunk_tree_failover_enabled supersedes its half-open inbound of
    # the same object instead of declining; pre-failover receivers drop
    # the field and keep the old decline-until-stale behavior.
    reroot: bool = False


@message("push_chunk")
class PushChunk:
    object_id: bytes
    chunk: bytes
    # integrity plane: per-chunk crc32 — wire corruption is caught at
    # chunk granularity, before the bad bytes enter the reassembly
    crc: "Optional[int]" = None


@message("push_end")
class PushEnd:
    object_id: bytes


@message("push_abort")
class PushAbort:
    object_id: bytes


@message("push_offer")
class PushOffer:
    object_id: bytes
    size: int
    is_error: bool = False
    shm_path: "Optional[str]" = None
    # integrity plane: crc of the offered payload — the same-host shm
    # fast path verifies the segment bytes it copies
    crc: "Optional[int]" = None
    # data-plane pipeline: the accepting node's subtree (see
    # PushBegin.downstream) — after adopting/copying the offered
    # segment it relays the object to these children.
    downstream: "Optional[list]" = None


@message("push_object")
class PushObject:
    object_id: bytes
    to_address: str
    # data-plane pipeline: subtree the destination should relay to
    # after receiving (see PushBegin.downstream).
    downstream: "Optional[list]" = None


# Handler is registered through RpcServer.register_data (raw-frame
# dispatch path), not the pickled-message registry the checker scans.
@message("push_chunk_data")  # raycheck: disable=RC06 — registered via register_data, not the pickled-message registry
class PushChunkData:
    # Header of the raw-data-frame chunk (wire v4): the chunk bytes
    # themselves travel out of band as the frame's unpickled payload,
    # landed by recv_into at OFFSET in the receiver's preallocated
    # segment. crc is the chunk digest, verified on the landed slice
    # while it is cache-hot — before any cut-through forward.
    object_id: bytes
    offset: int
    crc: "Optional[int]" = None


@message("pull_object")
class PullObject:
    # Ask a raylet to pull OBJECT_ID from the cluster (directory
    # lookup + holder fetch, deduped with any in-flight pull). The
    # flat broadcast topology and the driver's re-pull convergence
    # fallback both ride this; returns {"ok": bool} once the local
    # replica is sealed (or the pull failed).
    object_id: bytes
    # Optional hint: try this holder address first (the broadcast
    # planner knows who has it; skips a directory round trip).
    from_address: "Optional[str]" = None


@message("heartbeat")
class Heartbeat:
    node_id: str
    available: dict
    resources: dict
    # optional-with-default (schema evolution rules above): the node's
    # overload-plane counters — sheds, backpressure, breaker states
    overload: "Optional[dict]" = None
    # integrity-plane counters (corruption detections, discarded
    # replicas, bytes verified) — same evolution posture
    integrity: "Optional[dict]" = None
    # serve-resilience counters (unhealthy replicas, completed drains,
    # router exclusions, backpressured requests) — same evolution
    # posture: an old sender omits it, the GCS keeps {}
    serve: "Optional[dict]" = None
    # warm worker-pool counters (idle size, warm hits/misses, returns,
    # reaps, create-latency p50) — same evolution posture
    worker_pool: "Optional[dict]" = None
    # seconds left on a pending preemption notice this raylet received
    # (drain plane): the GCS starts a graceful drain inside the window.
    # Same evolution posture — an old sender omits it, no drain starts.
    preempt_notice_s: "Optional[float]" = None
    # live daemon-thread roots on the node ({thread name -> root
    # function label}, the ThreadRegistry's view) — `cli.py status`
    # shows them and raycheck RC16 names the same labels, so a report
    # maps straight to a running thread. Same evolution posture.
    threads: "Optional[dict]" = None


@message("object_add_location")
class ObjectAddLocation:
    object_id: bytes
    node_id: str
    size: int = 0


@message("object_add_locations")
class ObjectAddLocations:
    node_id: str
    entries: list


@message("object_remove_location")
class ObjectRemoveLocation:
    object_id: bytes
    node_id: str


@message("object_locations")
class ObjectLocations:
    object_id: bytes


@message("object_wait_location")
class ObjectWaitLocation:
    object_id: bytes
    timeout_s: float = 30.0


@message("get_object")
class GetObject:
    object_id: bytes


# ----------------------------------------------------------------------
# Control-plane schemas — every method registered by gcs_server.serve()
# and raylet_server.serve() declares its fields here; raycheck RC06/RC07
# joins these against the registrations and every call site, so a
# drifted kwarg or renamed method fails the tier-1 static gate instead
# of a runtime path a test may never exercise. Mutation methods carry
# the reserved optional ``token`` consumed by @token_deduped.
# ----------------------------------------------------------------------

# -- GCS: node table / failure detection


@message("register_node")
class RegisterNode:
    node_id: str
    address: str
    resources: dict


@message("drain_node")
class DrainNode:
    node_id: str
    # optional-with-default (schema evolution rules above): why the
    # drain was requested ("preempted" | "scale_down" | operator text)
    reason: str = ""
    # per-call override of Config.drain_deadline_s; None uses the knob
    deadline_s: "Optional[float]" = None
    # drain_node is a mutation (@token_deduped): a retried frame after
    # a lost ack must not double-run the migration fan-out
    token: str = ""


@message("cluster_view")
class ClusterView:
    pass


# -- GCS: internal KV


@message("kv_put")
class KvPut:
    ns: str
    key: bytes
    value: bytes


@message("kv_get")
class KvGet:
    ns: str
    key: bytes


@message("kv_del")
class KvDel:
    ns: str
    key: bytes


@message("kv_keys")
class KvKeys:
    ns: str
    prefix: bytes = b""


# -- GCS: actor management


@message("actor_create")
class ActorCreate:
    actor_id: str
    cls_bytes: bytes
    args_bytes: bytes
    resources: dict
    max_restarts: int = 0
    name: str = ""
    owner: str = ""
    token: str = ""


@message("actor_create_batch")
class ActorCreateBatch:
    # rows: {actor_id, cls_bytes, args_bytes, resources, max_restarts,
    # name, owner} — the client coalescer drains queued creates into
    # one frame; the reply carries one typed result row per actor
    # (state + error), so partial failure never loses a row. One token
    # dedupes the WHOLE batch.
    creates: list
    token: str = ""


@message("actor_kill_batch")
class ActorKillBatch:
    # rows: {actor_id, no_restart} — same coalescing contract as
    # actor_create_batch, kills fanned out per hosting node
    kills: list
    token: str = ""


@message("actor_get")
class ActorGet:
    actor_id: str


@message("actor_wait")
class ActorWait:
    # long-poll: blocks server-side until the actor leaves the
    # PENDING/RESTARTING limbo (reaches ALIVE with an address, or
    # DEAD) or timeout_s lapses — replaces the client's actor_get
    # hot-poll loop (wait_object-style blocking pattern)
    actor_id: str
    timeout_s: float = 30.0


@message("actor_by_name")
class ActorByName:
    name: str


@message("actor_kill")
class ActorKill:
    actor_id: str
    no_restart: bool = True
    token: str = ""


@message("actor_list")
class ActorList:
    pass


@message("report_actor_failure")
class ReportActorFailure:
    actor_id: str
    token: str = ""


# -- GCS: placement groups


@message("pg_create")
class PgCreate:
    pg_id: str
    bundles: list
    strategy: str = "PACK"
    token: str = ""


@message("pg_get")
class PgGet:
    pg_id: str


@message("pg_remove")
class PgRemove:
    pg_id: str
    token: str = ""


@message("pg_pending")
class PgPending:
    pass


# -- GCS: jobs / liveness


@message("job_view")
class JobView:
    pass


@message("ping")
class Ping:
    pass


# -- GCS: pubsub (long-poll channels)


@message("pubsub_subscribe")
class PubsubSubscribe:
    subscriber_id: str
    channel: str
    key: "Optional[str]" = None


@message("pubsub_unsubscribe")
class PubsubUnsubscribe:
    subscriber_id: str
    channel: "Optional[str]" = None
    key: "Optional[str]" = None


@message("pubsub_publish")
class PubsubPublish:
    channel: str
    key: str
    message: object


@message("pubsub_poll")
class PubsubPoll:
    subscriber_id: str
    timeout_s: float = 30.0


# -- raylet: task plane


@message("submit_task")
class SubmitTask:
    spec: dict


@message("submit_task_batch")
class SubmitTaskBatch:
    # specs: list of the same spec dicts submit_task carries, coalesced
    # by the client-side submit batcher (dispatch fast lane). The reply
    # carries one result row per spec — {accepted, reason?,
    # retry_after_s?} — so backpressure is PER ROW: one frame can
    # partially succeed, and only the shed rows retry at the hinted
    # pace (RetryLaterError semantics carried in-band instead of
    # failing the whole frame).
    specs: list


@message("task_state")
class TaskState:
    task_id: str


@message("wait_task")
class WaitTask:
    task_id: str
    timeout_s: float = 10.0


# -- raylet: object plane (unary surface; push_*/get_object above)


@message("wait_object")
class WaitObject:
    object_id: bytes
    timeout_s: float = 10.0


@message("free_objects")
class FreeObjects:
    object_ids: list


# -- raylet: actor execution


@message("create_actor")
class CreateActor:
    actor_id: str
    cls_bytes: bytes
    args_bytes: bytes
    resources: dict
    incarnation: int = 0


@message("actor_call")
class ActorCall:
    actor_id: str
    method_name: str
    args_bytes: bytes


@message("kill_actor")
class KillActor:
    actor_id: str


@message("kill_actor_batch")
class KillActorBatch:
    # ids of actors hosted on this node, one frame per node per
    # actor_kill_batch (GCS fan-out); reply carries per-actor rows
    actor_ids: list


# -- raylet: placement-group 2PC


@message("prepare_bundle")
class PrepareBundle:
    pg_id: str
    bundle_index: int
    bundle: dict


@message("commit_bundle")
class CommitBundle:
    pg_id: str
    bundle_index: int
    bundle: dict


@message("return_bundle")
class ReturnBundle:
    pg_id: str
    bundle_index: int
    bundle: dict
    committed: bool = False


# -- raylet: preemption notices (drain plane)


@message("preempt_notice")
class PreemptNotice:
    """Raylet: the infrastructure (or the fault plane's seeded
    `preempt_node` storm kind) announces this node will be evicted in
    ``notice_s`` seconds. The raylet records the deadline and reports
    the remaining window on its next heartbeat so the GCS can start a
    graceful drain inside it."""
    notice_s: float
    # optional provenance for logs/metrics ("storm" | "spot" | ...)
    reason: str = ""


# -- raylet: stats


@message("node_stats")
class NodeStats:
    pass


# -- observability plane: flight-recorder collection


@message("perf_dump")
class PerfDump:
    """Raylet: return this node's flight-recorder snapshot (recent
    spans/events, drop count, heartbeat-measured clock offset)."""
    pass


@message("collect_timeline")
class CollectTimeline:
    """GCS: fan perf_dump out to every alive raylet and return all
    snapshots plus the GCS's own, for `cli.py timeline`."""
    # per-node collection timeout; a dead/slow node is reported as an
    # error entry instead of stalling the merge
    per_node_timeout_s: float = 5.0
