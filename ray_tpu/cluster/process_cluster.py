"""Process-separated cluster: real OS processes per role, driven by tests.

``ProcessCluster`` mirrors the reference's multi-node-on-one-host test rig
(python/ray/cluster_utils.py:101 Cluster.add_node:170/remove_node:244 and
_private/services.py:1566 start_raylet): it spawns one GCS server process
and one raylet server process per node, and can SIGKILL any of them — a
*real* node death, detected by the GCS heartbeat manager, not a method
call.

``ClusterClient`` is the driver: it submits tasks to raylet processes
(spillback-retrying across nodes), keeps the lineage needed to resubmit
work lost to node death (reference: TaskManager::ResubmitTask), proxies
actor calls to the actor's current node with re-resolution on restart,
and fetches results over the chunked object-transfer plane.
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.cluster import overload as _overload
from ray_tpu.cluster import protocol
from ray_tpu.cluster.rpc import RpcClient, RpcConnectionError
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    RayActorError,
    RetryLaterError,
    WorkerCrashedError,
)

logger = logging.getLogger(__name__)


def _spawn(args: List[str], scrape: str, timeout: float = 30.0,
           extra_env: Optional[Dict[str, str]] = None
           ) -> Tuple[subprocess.Popen, List[str]]:
    """Start a server process and scrape its announce line from stdout."""
    # Control-plane processes never touch the accelerator: PYTHONPATH
    # is pinned to the package root so site hooks that eagerly register
    # accelerator plugins (and import jax at interpreter start) don't
    # slow down or wedge every raylet/GCS process, and JAX_PLATFORMS is
    # forced to a resolvable backend (cluster/child_env.py — shared
    # with the worker pools and the command provider).
    from ray_tpu.cluster.child_env import sanitized_env

    env = sanitized_env(pin_pythonpath=True)
    if extra_env:
        # per-process overrides: fault-injection plans
        # (RAY_TPU_FAULT_PLAN, cluster/fault_plane.py) and config flags
        env.update(extra_env)
    proc = subprocess.Popen(
        [sys.executable, "-m"] + args, stdout=subprocess.PIPE,
        stderr=None, env=env, text=True)
    deadline = time.monotonic() + timeout
    line = ""
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(
                f"{args[0]} exited during startup "
                f"(rc={proc.poll()})")
        if line.startswith(scrape):
            return proc, line.split()
    raise RuntimeError(f"{args[0]} did not announce within {timeout}s")


class ProcessCluster:
    """Spawns and kills the cluster's real processes."""

    def __init__(self, heartbeat_period_ms: int = 50,
                 num_heartbeats_timeout: int = 10,
                 storage_path: str = "",
                 gcs_env: Optional[Dict[str, str]] = None):
        self._gcs_args = [
            "--heartbeat-period-ms", str(heartbeat_period_ms),
            "--num-heartbeats-timeout", str(num_heartbeats_timeout)]
        if storage_path:
            self._gcs_args += ["--storage", storage_path]
        self._gcs_env = dict(gcs_env or {})
        self.gcs_proc, fields = _spawn(
            ["ray_tpu.cluster.gcs_server"] + self._gcs_args,
            "GCS_ADDRESS", extra_env=self._gcs_env)
        self.gcs_address = fields[1]
        self.raylets: Dict[str, subprocess.Popen] = {}  # node_id -> proc
        self.node_addresses: Dict[str, str] = {}

    def restart_gcs(self, env: Optional[Dict[str, str]] = None) -> None:
        """Bring the GCS back on the SAME address after a kill — the
        reference's GCS fault-tolerance scenario (tests/
        test_gcs_fault_tolerance.py): raylets keep running, heartbeats
        re-register, state reloads from table storage. ``env`` replaces
        the GCS's extra environment for the new incarnation (pass ``{}``
        to shed a fault plan the old incarnation ran under)."""
        if self.gcs_proc.poll() is None:
            self.kill_gcs()
        if env is not None:
            self._gcs_env = dict(env)
        port = self.gcs_address.rsplit(":", 1)[1]
        self.gcs_proc, fields = _spawn(
            ["ray_tpu.cluster.gcs_server", "--port", port]
            + self._gcs_args, "GCS_ADDRESS", timeout=60.0,
            extra_env=self._gcs_env)
        assert fields[1] == self.gcs_address, (fields, self.gcs_address)

    def add_node(self, num_cpus: float = 2,
                 resources: Optional[Dict[str, float]] = None,
                 num_workers: Optional[int] = None,
                 object_store_memory: Optional[int] = None,
                 extra_env: Optional[Dict[str, str]] = None) -> str:
        import json

        node_resources = dict(resources or {})
        node_resources.setdefault("CPU", float(num_cpus))
        args = ["ray_tpu.cluster.raylet_server", "--gcs", self.gcs_address,
                "--resources", json.dumps(node_resources),
                "--num-workers", str(num_workers or max(1, int(num_cpus)))]
        if object_store_memory:
            args += ["--object-store-memory", str(object_store_memory)]
        proc, fields = _spawn(args, "RAYLET_ADDRESS", timeout=60.0,
                              extra_env=extra_env)
        address, node_id = fields[1], fields[3]
        self.raylets[node_id] = proc
        self.node_addresses[node_id] = address
        return node_id

    def node_stats(self, node_id: str) -> dict:
        client = RpcClient(self.node_addresses[node_id])
        try:
            return client.call("node_stats", timeout=10.0)
        finally:
            client.close()

    def preempt_node(self, node_id: str, notice_s: float = 2.0,
                     reason: str = "preempted") -> dict:
        """Deliver a spot-provider preemption notice to a raylet: the
        node reports it on its next heartbeat and the GCS drains it
        inside the window. The eviction itself (kill_node after
        notice_s) is the caller's job — providers never promise the
        drain finishes first."""
        client = RpcClient(self.node_addresses[node_id])
        try:
            return client.call("preempt_notice", notice_s=float(notice_s),
                               reason=reason, timeout=10.0)
        finally:
            client.close()

    def kill_node(self, node_id: str, sig: int = signal.SIGKILL) -> None:
        """Hard-kill a raylet process — node death as the OS sees it."""
        proc = self.raylets.pop(node_id, None)
        if proc is None:
            raise KeyError(f"unknown node {node_id}")
        proc.send_signal(sig)
        proc.wait(timeout=10)

    def remove_node(self, node_id: str) -> None:
        """Graceful scale-down: drain through the GCS first (so actors /
        PGs reschedule off the node), then stop the process (reference:
        `ray stop` on a worker node → NodeManager drain)."""
        try:
            client = RpcClient(self.gcs_address)
            try:
                client.call("drain_node", node_id=node_id, timeout=15.0)
            finally:
                client.close()
        except Exception as e:
            # GCS gone: fall through to process termination
            logger.debug("graceful drain of node %s failed: %r",
                         node_id[:8], e)
        proc = self.raylets.pop(node_id, None)
        if proc is None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5)

    def kill_gcs(self, sig: int = signal.SIGKILL) -> None:
        self.gcs_proc.send_signal(sig)
        self.gcs_proc.wait(timeout=10)

    def wait_for_nodes(self, count: int, timeout: float = 30.0) -> None:
        client = RpcClient(self.gcs_address)
        try:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                view = client.call("cluster_view", timeout=10.0)
                alive = [n for n in view["nodes"].values() if n["alive"]]
                if len(alive) >= count:
                    return
                time.sleep(0.05)
            raise TimeoutError(
                f"only {len(alive)} nodes alive after {timeout}s")
        finally:
            client.close()

    def shutdown(self) -> None:
        for proc in self.raylets.values():
            try:
                proc.kill()
                proc.wait(timeout=5)
            except Exception as e:
                logger.debug("raylet pid %s kill failed: %r",
                             getattr(proc, "pid", "?"), e)
        self.raylets.clear()
        try:
            self.gcs_proc.kill()
            self.gcs_proc.wait(timeout=5)
        except Exception as e:
            logger.debug("gcs pid %s kill failed: %r",
                         getattr(self.gcs_proc, "pid", "?"), e)


class ClusterRef:
    """Driver-side handle to an object produced in the cluster."""

    __slots__ = ("object_id", "task_id", "node_id")

    def __init__(self, object_id: bytes, task_id: str = "",
                 node_id: str = ""):
        self.object_id = object_id
        self.task_id = task_id
        self.node_id = node_id  # node the producing task was sent to

    def hex(self) -> str:
        return self.object_id.hex()

    def __repr__(self):
        return f"ClusterRef({self.object_id.hex()[:12]})"


class ClusterActorHandle:
    __slots__ = ("_client", "actor_id")

    def __init__(self, client: "ClusterClient", actor_id: str):
        self._client = client
        self.actor_id = actor_id

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        client = self._client
        actor_id = self.actor_id

        def _call(*args, **kwargs):
            return client._actor_call(actor_id, name, args, kwargs)

        _call.__name__ = name
        return _call


class _ActorBatcher:
    """Client-side submit coalescer for the batched actor-lifecycle
    RPCs: concurrent ``create_actor``/``kill_actor`` callers enqueue
    rows, the first submitter becomes the drainer and flushes up to
    ``actor_batch_max`` rows per ``actor_create_batch`` /
    ``actor_kill_batch`` frame after an ``actor_batch_linger_s`` linger
    (long enough for a burst to pile up, short enough to be invisible
    on a lone call). One request token per flushed frame; per-row
    results fan back to their callers through events."""

    def __init__(self, name: str, flush_fn, linger_s: float,
                 max_batch: int):
        self._name = name
        # flush_fn(rows) -> {"results": [row, ...]} — owns the wire
        # call (and its request token) so the RPC site stays a literal
        # the wire-conformance checker can join against the schema
        self._flush_fn = flush_fn
        self._linger_s = linger_s
        self._max = max(1, max_batch)
        self._lock = threading.Lock()
        self._queue: List[Tuple[dict, dict]] = []
        self._draining = False

    def submit(self, row: dict, timeout: float = 120.0) -> dict:
        slot: Dict[str, Any] = {"event": threading.Event(),
                                "result": None, "error": None}
        with self._lock:
            self._queue.append((row, slot))
            leader = not self._draining
            if leader:
                self._draining = True
        if leader:
            self._drain()
        if not slot["event"].wait(timeout):
            raise GetTimeoutError(
                f"batched {self._name} row did not complete "
                f"within {timeout}s")
        if slot["error"] is not None:
            raise slot["error"]
        return slot["result"]

    def _drain(self) -> None:
        try:
            while True:
                time.sleep(self._linger_s)  # let the burst accumulate
                with self._lock:
                    batch = self._queue[:self._max]
                    del self._queue[:self._max]
                    if not batch:
                        self._draining = False
                        return
                rows = [r for r, _ in batch]
                try:
                    reply = self._flush_fn(rows)
                    for (_, slot), res in zip(batch, reply["results"]):
                        slot["result"] = res
                        slot["event"].set()
                except BaseException as e:  # noqa: BLE001
                    # frame-level failure: every row in it fails typed
                    for _, slot in batch:
                        slot["error"] = e
                        slot["event"].set()
        except BaseException:
            # the drainer must never die with followers still parked
            with self._lock:
                orphans = self._queue[:]
                self._queue.clear()
                self._draining = False
            for _, slot in orphans:
                slot["error"] = RuntimeError(
                    f"{self._name} batcher drain failed")
                slot["event"].set()
            raise


def _binomial_plan(nodes: List[str], addr_of: Dict[str, str]) -> list:
    """Binomial chunk-tree plan: the first pending node becomes a
    child and takes (half - 1) of the remainder as ITS subtree; depth
    is ceil(log2(N+1)) and every interior node forwards while it still
    receives. Returns ``[[address, subtree], ...]``."""
    out: list = []
    while nodes:
        half = (len(nodes) + 1) // 2
        head, sub, nodes = nodes[0], nodes[1:half], nodes[half:]
        out.append([addr_of[head], _binomial_plan(sub, addr_of)])
    return out


def _chain_plan(nodes: List[str], addr_of: Dict[str, str]) -> list:
    """Single-successor chain: depth N, fan-out 1 at every hop — the
    max-depth stress shape for cut-through forwarding."""
    plan: list = []
    for nid in reversed(nodes):
        plan = [[addr_of[nid], plan]]
    return plan


def _plan_depth(plan: list) -> int:
    if not plan:
        return 0
    return 1 + max(_plan_depth(sub) for _, sub in plan)


class ClusterClient:
    """The driver process's connection to a ProcessCluster."""

    # plan of the most recent broadcast() (topology/depth/fanout) —
    # bench and tests read it; None until the first broadcast
    last_broadcast_plan: Optional[Dict[str, Any]] = None

    def __init__(self, gcs_address: str):
        self.gcs_address = gcs_address
        from collections import OrderedDict

        from ray_tpu._private.config import Config
        from ray_tpu.cluster.rpc import ReconnectingRpcClient

        self.gcs = ReconnectingRpcClient(gcs_address)
        self._raylet_clients: Dict[str, RpcClient] = {}  # address -> client
        # return_id -> task spec, kept for node-death resubmission;
        # LRU-bounded like the in-process runtime's lineage cache
        self._lineage: "OrderedDict[bytes, dict]" = OrderedDict()
        self._retries: Dict[bytes, int] = {}
        self._lineage_cap = 10_000
        self._lock = threading.Lock()
        self._counter = 0
        cfg = Config.instance()
        # master switch: with worker_pool_enabled off, create/kill take
        # the exact pre-batching serial RPCs (one frame per actor)
        self._batching = cfg.worker_pool_enabled
        self._create_batcher = _ActorBatcher(
            "actor_create_batch",
            lambda rows: self.gcs.call(
                "actor_create_batch", creates=rows,
                token=self._next_id("tok"), timeout=120.0),
            cfg.actor_batch_linger_s, cfg.actor_batch_max)
        self._kill_batcher = _ActorBatcher(
            "actor_kill_batch",
            lambda rows: self.gcs.call(
                "actor_kill_batch", kills=rows,
                token=self._next_id("tok"), timeout=120.0),
            cfg.actor_batch_linger_s, cfg.actor_batch_max)
        # ---- dispatch fast lane (driver side) ----
        # master switch: off restores the exact serial submit_task RPC,
        # per-submit func pickling, and always-inline args
        self._fastlane = cfg.dispatch_fastlane_enabled
        self._submit_linger_s = cfg.dispatch_batch_linger_s
        self._submit_batch_max = cfg.dispatch_batch_max
        self._inline_arg_max = (cfg.dispatch_inline_arg_max
                                if cfg.dispatch_inline_arg_max > 0
                                else cfg.max_direct_call_object_size)
        # one submit coalescer per raylet address (created lazily: the
        # flush target is the node the spec was routed to)
        self._submit_batchers: Dict[str, _ActorBatcher] = {}
        # func -> pickled bytes: the template memo for this tier — a
        # hot loop resubmitting the same function re-encodes only args
        # and ids, not the closure (bounded; unhashable funcs skip it)
        self._func_bytes: Dict[Any, bytes] = {}
        # node_id -> monotonic deadline: a raylet whose connection just
        # failed is SUSPECT until the deadline. The GCS needs a full
        # heartbeat-timeout window to declare it dead, and until then
        # the node looks maximally free (its availability never drains)
        # — so without this hint every placement decision piles onto
        # the corpse, and under a concurrent workload the whole driver
        # stalls until the verdict. A suspect node is only deprioritized
        # (it still takes work when it is the only feasible node), so a
        # transient conn blip costs a few seconds of avoidance, never
        # livelock.
        self._suspect_until: Dict[str, float] = {}

    # ------------------------------------------------------------ plumbing
    def _next_id(self, prefix: str) -> str:
        with self._lock:
            self._counter += 1
            return f"{prefix}-{os.getpid()}-{self._counter:08d}"

    def _raylet(self, address: str) -> RpcClient:
        c = self._raylet_clients.get(address)
        if c is None or c.closed:
            c = RpcClient(address)
            self._raylet_clients[address] = c
        return c

    def _submit_batcher(self, address: str) -> _ActorBatcher:
        """The per-raylet submit coalescer (dispatch fast lane):
        concurrent ``_submit_spec`` callers routed to the same node
        pile their specs onto one ``submit_task_batch`` frame; per-row
        accept/backpressure results fan back through the batcher."""
        with self._lock:
            b = self._submit_batchers.get(address)
            if b is None:
                b = _ActorBatcher(
                    "submit_task_batch",
                    lambda rows, _a=address: self._raylet(_a).call(
                        "submit_task_batch", specs=rows, timeout=30.0),
                    self._submit_linger_s, self._submit_batch_max)
                self._submit_batchers[address] = b
            return b

    def _dumps_func(self, func) -> bytes:
        """Pickle a task function, memoized per function object on the
        fast lane — resubmitting the same function skips cloudpickle
        entirely (the closure was frozen at first submit, the
        template contract)."""
        if self._fastlane:
            try:
                data = self._func_bytes.get(func)
            except TypeError:  # unhashable callable
                return protocol.dumps(func)
            if data is None:
                data = protocol.dumps(func)
                if len(self._func_bytes) < 4096:
                    self._func_bytes[func] = data
            return data
        return protocol.dumps(func)

    def cluster_view(self) -> dict:
        return self.gcs.call("cluster_view", timeout=10.0)

    def subscriber(self, poll_timeout_s: float = 5.0):
        """A Subscriber over the GCS-hosted pubsub channels (ACTOR, NODE,
        OBJECT_LOCATION, LOG, ERROR). Caller owns close()."""
        from ray_tpu.pubsub import Subscriber

        sid = self._next_id("sub")
        return Subscriber(
            sid,
            poll_fn=lambda subscriber_id, timeout: self.gcs.call(
                "pubsub_poll", subscriber_id=subscriber_id,
                timeout_s=timeout, timeout=timeout + 10.0),
            subscribe_fn=lambda **kw: self.gcs.call(
                "pubsub_subscribe", timeout=10.0, **kw),
            unsubscribe_fn=lambda **kw: self.gcs.call(
                "pubsub_unsubscribe", timeout=10.0, **kw),
            poll_timeout_s=poll_timeout_s,
        )

    def _alive_nodes(self) -> List[Tuple[str, dict]]:
        view = self.cluster_view()
        return [(nid, info) for nid, info in view["nodes"].items()
                if info["alive"]]

    def _mark_suspect(self, node_id: str, ttl_s: float = 3.0) -> None:
        """Steer placement away from a conn-failed raylet for ttl_s —
        long enough to bridge the gap until the GCS's heartbeat verdict
        lands, short enough that a false alarm self-heals."""
        with self._lock:
            self._suspect_until[node_id] = time.monotonic() + ttl_s

    def _clear_suspect(self, node_id: str) -> None:
        """A successful dispatch is proof of life: drop the suspicion
        early instead of waiting out the TTL, so a reconnected node
        regains full placement eligibility on its first accepted
        frame."""
        with self._lock:
            self._suspect_until.pop(node_id, None)

    def _is_suspect(self, node_id: str) -> bool:
        with self._lock:
            deadline = self._suspect_until.get(node_id)
            if deadline is None:
                return False
            if deadline <= time.monotonic():
                del self._suspect_until[node_id]
                return False
            return True

    def _pick_node(self, resources: Dict[str, float],
                   exclude: Optional[set] = None) -> Optional[Tuple[str, dict]]:
        """Most-available feasible node (driver-side lease targeting;
        reference lease_policy.cc picks by locality, we pick by headroom).
        Suspect nodes (recent conn failure, no death verdict yet) lose
        to any non-suspect candidate but stay eligible as a last
        resort."""
        exclude = exclude or set()
        best = None
        best_score = None
        for nid, info in self._alive_nodes():
            if nid in exclude:
                continue
            if any(info["resources"].get(k, 0.0) < v
                   for k, v in resources.items()):
                continue
            avail = info["available"]
            score = sum(avail.values())
            if any(avail.get(k, 0.0) < v for k, v in resources.items()):
                score -= 1e6  # feasible-but-busy: allowed, deprioritized
            if self._is_suspect(nid):
                score -= 1e9  # likely dead: below every healthy option
            if info.get("state") == "DRAINING":
                score -= 1e9  # leaving soon: below every healthy option
            if best_score is None or score > best_score:
                best, best_score = (nid, info), score
        return best

    # ---------------------------------------------------------------- tasks
    def submit(self, func, args: tuple = (), kwargs: Optional[dict] = None,
               resources: Optional[Dict[str, float]] = None,
               max_retries: int = 3, node_id: Optional[str] = None,
               runtime_env: Optional[dict] = None) -> ClusterRef:
        task_id = self._next_id("task")
        return_id = os.urandom(28)
        spec = {
            "task_id": task_id,
            "func": self._dumps_func(func),
            "args": [self._pack_arg(a) for a in args],
            "kwargs": {k: self._pack_arg(v)
                       for k, v in (kwargs or {}).items()},
            "resources": dict(resources or {"CPU": 1.0}),
            "return_id": return_id,
        }
        if runtime_env is not None:
            # normalize driver-side: pip/conda envs materialize here,
            # py_modules dirs package into pymod:// URIs seeded to THIS
            # tier's KV (the GCS server) — the raylet's
            # _stage_py_modules fetches from the same store, so remote
            # nodes without the archive can resolve it
            from ray_tpu._private.runtime_env import normalize
            from ray_tpu._private.runtime_env_packaging import (
                KV_NAMESPACE,
            )

            spec["runtime_env"] = normalize(
                runtime_env,
                kv_put=lambda k, v: self.kv_put(k, v, ns=KV_NAMESPACE))
        # observability plane: a sampled trace rides inside the spec, so
        # the raylet's execution span parents to the driver's current
        # span across the wire (reference: tracing_helper.py carrying
        # context in the task spec)
        from ray_tpu.util import tracing as _tracing
        if _tracing.enabled():
            ctx = _tracing.current_context()
            if ctx is not None and ctx.sampled:
                spec["trace_context"] = ctx.to_dict()
        assigned = self._submit_spec(spec, node_hint=node_id)
        ref = ClusterRef(return_id, task_id, assigned)
        with self._lock:
            self._lineage[return_id] = spec
            while len(self._lineage) > self._lineage_cap:
                old, _ = self._lineage.popitem(last=False)
                self._retries.pop(old, None)
            self._retries[return_id] = max_retries
        return ref

    def _pack_arg(self, value) -> tuple:
        if isinstance(value, ClusterRef):
            return ("ref", value.object_id)
        data = protocol.dumps(value)
        if self._fastlane and len(data) > self._inline_arg_max:
            # out-of-band handoff (dispatch fast lane): an oversized
            # arg is stored ONCE through the object plane — the
            # executing node resolves it over the shm fast path — so
            # the submit frame stays small instead of carrying the
            # payload on every wire hop
            return ("ref", self.put(value).object_id)
        return ("v", data)

    def _submit_spec(self, spec: dict, node_hint: Optional[str] = None,
                     exclude: Optional[set] = None) -> str:
        """Send to a raylet; on rejection/conn-failure spill to the next
        node (grant-or-reject spillback, direct_task_transport.cc:295).
        A RetryLaterError is BACKPRESSURE, not rejection: the node is
        healthy but its bounded queue is full — sleep the hinted pace
        and offer the task again (possibly to a less loaded node)
        without excluding the pushing-back node."""
        exclude = set(exclude or ())
        hint = node_hint
        backpressure_deadline = time.monotonic() + 120.0
        attempts = 0
        while attempts < 8:
            target = None
            if hint and hint not in exclude:
                for nid, info in self._alive_nodes():
                    if nid == hint:
                        target = (nid, info)
                        break
                hint = None
            if target is None:
                target = self._pick_node(spec["resources"], exclude)
            if target is None:
                attempts += 1
                time.sleep(0.2)
                continue
            nid, info = target
            try:
                if self._fastlane and _overload.lane_enabled("dispatch"):
                    # fast lane: the spec rides a coalesced
                    # submit_task_batch frame with every other submit
                    # routed to this node in the linger window; the
                    # per-row reply mirrors the serial RPC's. The row
                    # token is stamped once and survives every retry of
                    # this spec (this dict is the retried object), so a
                    # frame replayed after a dropped reply dedupes on
                    # the raylet instead of double-queueing the task.
                    if not spec.get("token"):
                        spec["token"] = self._next_id("rowtok")
                    try:
                        reply = self._submit_batcher(
                            info["address"]).submit(spec, timeout=40.0)
                    except RetryLaterError:
                        # a shed is load pushback, not a lane defect:
                        # the frame round-tripped fine
                        _overload.lane_ok("dispatch")
                        raise
                    except BaseException:
                        _overload.lane_failed("dispatch")
                        raise
                    _overload.lane_ok("dispatch")
                else:
                    # serial safe path: operator switch off, or the
                    # dispatch lane breaker is open (degraded mode)
                    reply = self._raylet(info["address"]).call(
                        "submit_task", spec=spec, timeout=30.0)
            except RetryLaterError as e:
                if time.monotonic() >= backpressure_deadline:
                    raise
                time.sleep(e.retry_after_s)
                continue  # same node stays eligible; no attempt burned
            except (RpcConnectionError, TimeoutError):
                # remember the failure beyond this one task: until the
                # heartbeat verdict, the dead node looks maximally free
                # and would win every subsequent _pick_node
                self._mark_suspect(nid)
                attempts += 1
                exclude.add(nid)
                continue
            if reply.get("accepted"):
                # the last-resort pick answered after all: reconnected,
                # not dead — restore full eligibility immediately
                self._clear_suspect(nid)
                return nid
            if reply.get("reason") == "backpressure":
                # per-row backpressure from a batched frame: the
                # RetryLaterError semantics ride the row — same node
                # stays eligible, no attempt burned, hinted pace
                if time.monotonic() >= backpressure_deadline:
                    raise RetryLaterError(
                        f"node {nid[:8]} kept shedding submits for "
                        f"task {spec['task_id']}",
                        retry_after_s=reply.get("retry_after_s", 0.1))
                time.sleep(reply.get("retry_after_s", 0.05))
                continue
            attempts += 1
            exclude.add(nid)
        raise RuntimeError(
            f"no node accepted task {spec['task_id']} "
            f"(demand={spec['resources']})")

    def _resubmit(self, ref: ClusterRef) -> bool:
        """Lineage resubmission after node death (TaskManager::
        ResubmitTask, task_manager.cc:99)."""
        with self._lock:
            spec = self._lineage.get(ref.object_id)
            left = self._retries.get(ref.object_id, 0)
            if spec is None or left <= 0:
                return False
            self._retries[ref.object_id] = left - 1
        logger.warning("resubmitting task %s after node loss (%d retries "
                       "left)", spec["task_id"][:12], left - 1)
        ref.node_id = self._submit_spec(spec, exclude={ref.node_id})
        return True

    # ------------------------------------------------------------------ get
    def get(self, ref: ClusterRef, timeout: Optional[float] = 60.0) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                raise GetTimeoutError(
                    f"get of {ref.object_id.hex()[:8]} timed out")
            wait_s = min(remaining or 0.5, 0.5)
            reply = self.gcs.call(
                "object_wait_location", object_id=ref.object_id,
                timeout_s=wait_s, timeout=wait_s + 10.0)
            locations = reply["locations"]
            if not locations:
                # no copy anywhere: producer may have died — resubmit if
                # the producing node is gone and lineage allows
                if ref.node_id and not self._node_alive(ref.node_id):
                    if not self._resubmit(ref):
                        raise WorkerCrashedError(
                            f"object {ref.object_id.hex()[:8]} lost and "
                            "not recoverable")
                continue
            payload = self._fetch(locations, ref.object_id)
            if payload is None:
                continue  # all holders died mid-fetch; loop re-resolves
            is_error, data = payload
            value = protocol.loads_flat(data)
            if is_error:
                # the stored payload is the task's exception: re-raise it
                # in the driver (reference: RayTaskError re-raise on get)
                if isinstance(value, BaseException):
                    raise value
                raise RuntimeError(str(value))
            return value

    def wait(self, refs: List[ClusterRef], num_returns: int = 1,
             timeout: Optional[float] = None
             ) -> Tuple[List[ClusterRef], List[ClusterRef]]:
        """ray.wait semantics over the cluster: ready = a location
        exists in the GCS directory (the object is materialized on some
        node)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        pending = list(refs)
        ready: List[ClusterRef] = []
        while True:
            still: List[ClusterRef] = []
            for ref in pending:
                reply = self.gcs.call("object_locations",
                                      object_id=ref.object_id,
                                      timeout=10.0)
                if reply["locations"]:
                    ready.append(ref)
                else:
                    still.append(ref)
            pending = still
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.05)
        ready_set = {id(r) for r in ready[:num_returns]}
        ordered_ready = [r for r in refs if id(r) in ready_set]
        return (ordered_ready,
                [r for r in refs if id(r) not in ready_set])

    def _node_alive(self, node_id: str) -> bool:
        view = self.cluster_view()
        info = view["nodes"].get(node_id)
        return bool(info and info["alive"])

    def _node_address(self, node_id: str) -> Optional[str]:
        view = self.cluster_view()
        info = view["nodes"].get(node_id)
        return info["address"] if info and info["alive"] else None

    # --------------------------------------------------------- task state
    def task_state(self, ref: ClusterRef) -> str:
        """State of the task that produces ``ref`` on its assigned node:
        queued | running | done | failed | unknown | lost (node
        dead). The driver-side view of the reference's task-state API
        (GetTaskEvents over the GCS)."""
        address = self._node_address(ref.node_id) if ref.node_id else None
        if address is None:
            return "lost"
        try:
            reply = self._raylet(address).call(
                "task_state", task_id=ref.task_id, timeout=10.0)
        except (RpcConnectionError, TimeoutError):
            return "lost"
        return reply["state"]

    def wait_task(self, ref: ClusterRef,
                  timeout: float = 10.0) -> str:
        """Block on the producing raylet until the task reaches a
        terminal state (or the timeout lapses); returns the final
        state observed (terminal or not)."""
        address = self._node_address(ref.node_id) if ref.node_id else None
        if address is None:
            return "lost"
        try:
            reply = self._raylet(address).call(
                "wait_task", task_id=ref.task_id, timeout_s=timeout,
                timeout=timeout + 10.0)
        except (RpcConnectionError, TimeoutError):
            return "lost"
        return reply["state"]

    def _fetch(self, locations: List[dict], object_id: bytes
               ) -> Optional[Tuple[bool, bytes]]:
        from ray_tpu.cluster.byte_store import attach_shm, shm_key
        from ray_tpu.cluster.rpc import fetch_object

        for loc in locations:
            try:
                client = self._raylet(loc["address"])
            except (RpcConnectionError, OSError):
                continue
            # same-host fast path: read the holder's shm segment
            # directly instead of streaming over TCP (mirrors the
            # raylet-to-raylet path in raylet_server._fetch_from)
            try:
                info = client.call("get_object_info",
                                   object_id=object_id, timeout=10.0)
            except (RpcConnectionError, TimeoutError):
                continue
            if not info.get("present"):
                continue
            if info.get("shm_path"):
                seg = attach_shm(info["shm_path"])
                if seg is not None:
                    try:
                        payload = seg.get_bytes(shm_key(object_id))
                    except Exception:
                        payload = None
                    if payload is not None:
                        # trailer-aware slice + digest check (integrity
                        # plane): the bytes copied out of the holder's
                        # segment are verified before deserialization;
                        # a mismatch falls through to the chunked
                        # stream, which re-verifies end to end
                        from ray_tpu.cluster import integrity
                        from ray_tpu.exceptions import (
                            ObjectCorruptedError,
                        )

                        body, t_crc = integrity.split_shm(
                            payload, info["size"])
                        if body is not None:
                            crc = info.get("crc")
                            crc = crc if crc is not None else t_crc
                            try:
                                if integrity.verify_shm_reads():
                                    integrity.verify(body, crc,
                                                     "shm_read",
                                                     object_id)
                                return info["is_error"], bytes(body)
                            except ObjectCorruptedError:
                                logger.warning(
                                    "shm read of %s failed its digest;"
                                    " falling back to the stream",
                                    object_id.hex()[:8])
            result = fetch_object(client, object_id)
            if result is not None:
                return result
        return None

    def broadcast(self, ref: ClusterRef, node_ids: List[str]) -> int:
        """Pre-place an object's payload on a set of nodes through the
        push plane. With the data-plane pipeline ON (default) the
        driver plans ONE chunk tree (topology knob: binomial | chain |
        flat | auto) and hands the nested plan to the source raylet in
        a single push — interior nodes cut-through forward each chunk
        the moment it verifies, so tree depth costs latency per CHUNK,
        not per object, and same-host receivers adopt the producer's
        segment outright (zero bytes moved). OFF reproduces the exact
        pre-pipeline round-by-round driver fan-out (parity-pinned).
        Unconfirmed nodes converge through a pull_object fallback.
        Returns the number of nodes that confirmed a resident copy."""
        from ray_tpu._private.config import Config

        if not Config.instance().data_plane_pipeline_enabled:
            return self._broadcast_legacy(ref, node_ids)
        return self._broadcast_pipelined(ref, node_ids)

    def _broadcast_pipelined(self, ref: ClusterRef,
                             node_ids: List[str]) -> int:
        from ray_tpu._private.config import Config

        cfg = Config.instance()
        view = self.cluster_view()
        addr_of = {nid: info["address"]
                   for nid, info in view["nodes"].items()
                   if info["alive"]}
        reply = self.gcs.call("object_locations",
                              object_id=ref.object_id, timeout=10.0)
        holders = [loc["node_id"] for loc in reply["locations"]
                   if loc["node_id"] in addr_of]
        targets = [n for n in node_ids
                   if n not in holders and n in addr_of]
        if not targets or not holders:
            self.last_broadcast_plan = {"topology": "none", "depth": 0,
                                        "fanout": 0, "targets": 0}
            return 0
        src = holders[0]
        topology = cfg.data_plane_topology
        if topology == "auto":
            # small fans: the per-target pull dedup is simpler and the
            # tree's pipeline has nothing to overlap; larger fans get
            # the binomial chunk tree
            topology = "flat" if len(targets) <= 2 else "binomial"

        confirmed_set: set = set()
        if topology == "flat":
            plan = None
            calls = []
            for dst in targets:
                try:
                    calls.append((dst, self._raylet(addr_of[dst]).call_async(
                        "pull_object", object_id=ref.object_id,
                        from_address=addr_of[src])))
                except (RpcConnectionError, OSError):
                    continue
            for dst, call in calls:
                try:
                    if call.result(timeout=300.0).get("ok"):
                        confirmed_set.add(dst)
                except Exception:
                    continue  # unconfirmed: the re-pull rounds converge
        else:
            plan = (_chain_plan(targets, addr_of) if topology == "chain"
                    else _binomial_plan(targets, addr_of))
            for addr, subtree in plan:
                try:
                    self._raylet(addr_of[src]).call(
                        "push_object", object_id=ref.object_id,
                        to_address=addr, downstream=subtree or None,
                        timeout=60.0)
                except (RpcConnectionError, TimeoutError) as e:
                    # source unreachable for this child: the re-pull
                    # fallback below still converges the subtree
                    logger.debug(
                        "broadcast: push_object %s -> %s failed (%r); "
                        "subtree converges via re-pull",
                        addr_of[src], addr, e)
        self.last_broadcast_plan = {
            "topology": topology,
            "depth": _plan_depth(plan) if plan else 1,
            "fanout": len(plan) if plan else len(targets),
            "targets": len(targets)}
        # confirm + converge: wait on each target's store, then re-pull
        # stragglers (a dead interior node orphans its subtree; the
        # survivors fetch from any confirmed holder — satellite
        # contract: subtree converges via re-pull)
        deadline = time.monotonic() + 300.0
        for round_no in range(3):
            pending = [d for d in targets if d not in confirmed_set]
            if not pending or time.monotonic() >= deadline:
                break
            for dst in pending:
                if time.monotonic() >= deadline:
                    break
                try:
                    client = self._raylet(addr_of[dst])
                    if round_no > 0:
                        # straggler: actively re-pull instead of waiting
                        if client.call("pull_object",
                                       object_id=ref.object_id,
                                       timeout=70.0).get("ok"):
                            confirmed_set.add(dst)
                            continue
                    present = client.call(
                        "wait_object", object_id=ref.object_id,
                        timeout_s=(5.0 if round_no == 0 else 1.0),
                        timeout=60.0)["present"]
                    if present:
                        confirmed_set.add(dst)
                except RpcConnectionError:
                    continue  # node died mid-broadcast: stays unconfirmed
                except TimeoutError:
                    continue
        return len(confirmed_set)

    def _broadcast_legacy(self, ref: ClusterRef,
                          node_ids: List[str]) -> int:
        """The exact pre-pipeline broadcast (data_plane_pipeline_enabled
        off): round-by-round driver-coordinated binomial fan-out — each
        round, every node that already holds a copy pushes to one new
        node, so a B-byte broadcast to N nodes costs any single holder
        only O(log N) * B upload instead of N * B (reference broadcast
        pattern stressed by the 1 GiB -> 50 node object_store baseline;
        push path: object_manager.cc:302 + push_manager.h). Returns the
        number of nodes that confirmed a resident copy."""
        view = self.cluster_view()
        addr_of = {nid: info["address"]
                   for nid, info in view["nodes"].items()
                   if info["alive"]}
        reply = self.gcs.call("object_locations",
                              object_id=ref.object_id, timeout=10.0)
        # a dead node's location entry may linger until the async
        # deregistration lands: only fan out from holders that are alive
        holders = [loc["node_id"] for loc in reply["locations"]
                   if loc["node_id"] in addr_of]
        targets = [n for n in node_ids
                   if n not in holders and n in addr_of]
        self.last_broadcast_plan = {"topology": "legacy", "depth": 0,
                                    "fanout": 0, "targets": len(targets)}
        if not targets:
            return 0
        confirmed = 0
        pending = list(targets)
        rounds_without_progress = 0
        while pending and rounds_without_progress < 3:
            # every current holder feeds one pending target this round
            requested = []
            for src, dst in zip(list(holders), list(pending)):
                try:
                    # generous: enqueueing a push is cheap, but a node
                    # mid-transfer of GiB-scale chunks answers slowly
                    # on a saturated host
                    ok = self._raylet(addr_of[src]).call(
                        "push_object", object_id=ref.object_id,
                        to_address=addr_of[dst],
                        timeout=60.0).get("ok")
                except (RpcConnectionError, TimeoutError):
                    ok = False
                if ok:
                    requested.append(dst)
            pending = [d for d in pending if d not in set(requested)]
            # wait for this round's copies before fanning out from them
            progressed = False
            for dst in requested:
                client = self._raylet(addr_of[dst])
                deadline = time.monotonic() + 300.0
                while time.monotonic() < deadline:
                    try:
                        # block in the receiver's store instead of
                        # hot-polling has_object: wait_object parks on
                        # the store's condition variable and returns
                        # the moment the copy materializes
                        present = client.call(
                            "wait_object", object_id=ref.object_id,
                            timeout_s=5.0, timeout=60.0)["present"]
                    except RpcConnectionError:
                        # node DIED mid-broadcast: stays unconfirmed —
                        # partial results are the contract
                        break
                    except TimeoutError:
                        # merely slow (GiB transfer on a saturated
                        # host): keep waiting until the 300s deadline
                        continue
                    if present:
                        holders.append(dst)
                        confirmed += 1
                        progressed = True
                        break
            rounds_without_progress = (
                0 if progressed else rounds_without_progress + 1)
        return confirmed

    # ------------------------------------------------------------------ put
    def put(self, value: Any) -> ClusterRef:
        object_id = os.urandom(28)
        payload = protocol.dumps_flat(value)
        exclude: set = set()
        last_err: Optional[BaseException] = None
        # spill to the next holder on conn failure, like submits do: a
        # put routed to a just-died node (no heartbeat verdict yet) is
        # retriable on any other holder — and marking the node suspect
        # keeps the NEXT put from re-picking the corpse
        for _ in range(3):
            target = self._pick_node({}, exclude)
            if target is None:
                break
            nid, info = target
            try:
                self._raylet(info["address"]).call(
                    "put_object", object_id=object_id, payload=payload,
                    timeout=60.0)
            except (RpcConnectionError, TimeoutError) as e:
                self._mark_suspect(nid)
                exclude.add(nid)
                last_err = e
                continue
            return ClusterRef(object_id, "", nid)
        if last_err is not None:
            raise last_err
        raise RuntimeError("no alive nodes to hold the object")

    # ---------------------------------------------------------------- actors
    def create_actor(self, cls, args: tuple = (),
                     kwargs: Optional[dict] = None,
                     resources: Optional[Dict[str, float]] = None,
                     max_restarts: int = 0, name: str = ""
                     ) -> ClusterActorHandle:
        actor_id = self._next_id("actor")
        packed_args = ([self._pack_arg(a) for a in args],
                       {k: self._pack_arg(v)
                        for k, v in (kwargs or {}).items()})
        if self._batching:
            # coalesced path: the row rides an actor_create_batch frame
            # with everything else submitted this linger window; the
            # per-row reply carries the same view the serial RPC would
            # row token: a frame retried after a dropped reply (or
            # duplicated by the fault plane) replays this row from the
            # GCS dedupe cache instead of double-registering the actor
            view = self._create_batcher.submit({
                "actor_id": actor_id,
                "cls_bytes": protocol.dumps(cls),
                "args_bytes": protocol.dumps(packed_args),
                "resources": dict(resources or {"CPU": 1.0}),
                "max_restarts": max_restarts, "name": name,
                "token": self._next_id("rowtok"),
            }, timeout=120.0)
            if view.get("state") == "ERROR":
                # API parity with the serial path, where the GCS raises
                # this typed across the wire (e.g. name already taken)
                raise ValueError(
                    view.get("error", "actor creation failed"))
        else:
            # request token: the resilient GCS client may retry this
            # call after a lost ack, and the fault plane may duplicate
            # the frame — either way the mutation applies exactly once
            view = self.gcs.call(
                "actor_create", actor_id=actor_id,
                cls_bytes=protocol.dumps(cls),
                args_bytes=protocol.dumps(packed_args),
                resources=dict(resources or {"CPU": 1.0}),
                max_restarts=max_restarts, name=name,
                token=self._next_id("tok"), timeout=120.0)
        if view["state"] == "PENDING":
            logger.info("actor %s pending (no capacity yet)", actor_id)
        return ClusterActorHandle(self, actor_id)

    def get_actor(self, name: str) -> ClusterActorHandle:
        view = self.gcs.call("actor_by_name", name=name, timeout=10.0)
        return ClusterActorHandle(self, view["actor_id"])

    def actor_state(self, handle_or_id) -> dict:
        """The GCS's current record for an actor (state, node,
        incarnation, restarts, init_error) — a non-blocking snapshot;
        ``_actor_call`` uses the blocking ``actor_wait`` instead."""
        actor_id = getattr(handle_or_id, "actor_id", handle_or_id)
        return self.gcs.call("actor_get", actor_id=actor_id,
                             timeout=10.0)

    def _actor_call(self, actor_id: str, method: str, args: tuple,
                    kwargs: dict, timeout: float = 60.0) -> Any:
        """Route to the actor's current node; on failure re-resolve from
        the GCS (restart may have moved it) and retry until the actor is
        DEAD or the timeout lapses."""
        packed = ([self._pack_arg(a) for a in args],
                  {k: self._pack_arg(v) for k, v in kwargs.items()})
        args_bytes = protocol.dumps(packed)
        deadline = time.monotonic() + timeout
        last_err: Optional[BaseException] = None
        backoff = 0.05
        while time.monotonic() < deadline:
            # actor_wait long-polls server-side until the actor settles
            # (ALIVE-with-address or DEAD) — replaces the old
            # actor_get + flat sleep(0.1) hot-poll that burned a GCS
            # round-trip every 100ms per waiting caller
            wait_s = min(5.0, max(0.1, deadline - time.monotonic()))
            view = self.gcs.call("actor_wait", actor_id=actor_id,
                                 timeout_s=wait_s, timeout=wait_s + 10.0)
            state = view["state"]
            if state == "DEAD":
                detail = view.get("init_error") or ""
                raise ActorDiedError(
                    f"actor {actor_id} is dead "
                    f"(restarts used: {view['restarts_used']})"
                    + (f": {detail}" if detail else ""))
            if state != "ALIVE" or "address" not in view:
                # long-poll lapsed with the actor still in limbo:
                # capped exponential backoff before re-polling
                time.sleep(backoff)
                backoff = min(backoff * 2, 1.0)
                continue
            backoff = 0.05
            try:
                result = self._raylet(view["address"]).call(
                    "actor_call", actor_id=actor_id, method_name=method,
                    args_bytes=args_bytes,
                    timeout=max(1.0, deadline - time.monotonic()))
                return protocol.loads(result)
            except WorkerCrashedError as e:
                # the actor process died EXECUTING this call: surface it —
                # actor tasks are not retried by default (reference:
                # max_task_retries=0); the GCS restarts the actor in the
                # background for future calls
                raise RayActorError(
                    f"actor {actor_id} died while executing "
                    f"{method}: {e}") from e
            except (RpcConnectionError, TimeoutError, KeyError,
                    ConnectionError, OSError) as e:
                last_err = e
                time.sleep(0.2)  # node died or actor moving; re-resolve
        raise GetTimeoutError(
            f"actor call {actor_id}.{method} did not complete: "
            f"{last_err!r}")

    def kill_actor(self, handle: ClusterActorHandle,
                   no_restart: bool = True) -> None:
        if self._batching:
            # coalesced path: rides an actor_kill_batch frame; the GCS
            # marks every row DEAD under one lock hold and sends each
            # hosting raylet one kill frame instead of a serial
            # 10s-timeout RPC per actor
            self._kill_batcher.submit(
                {"actor_id": handle.actor_id, "no_restart": no_restart,
                 "token": self._next_id("rowtok")},
                timeout=60.0)
            return
        self.gcs.call("actor_kill", actor_id=handle.actor_id,
                      no_restart=no_restart,
                      token=self._next_id("tok"), timeout=30.0)

    # ------------------------------------------------------------------- PG
    def create_placement_group(self, bundles: List[Dict[str, float]],
                               strategy: str = "PACK") -> str:
        pg_id = os.urandom(18).hex()
        view = self.gcs.call("pg_create", pg_id=pg_id, bundles=bundles,
                             strategy=strategy,
                             token=self._next_id("tok"), timeout=120.0)
        return view["pg_id"]

    def pg_info(self, pg_id: str) -> dict:
        return self.gcs.call("pg_get", pg_id=pg_id, timeout=10.0)

    def remove_placement_group(self, pg_id: str) -> None:
        self.gcs.call("pg_remove", pg_id=pg_id,
                      token=self._next_id("tok"), timeout=60.0)

    # ----------------------------------------------------------------- free
    def free(self, refs: List[ClusterRef]) -> int:
        """Eagerly drop the payloads behind ``refs`` from every node
        holding a copy (``ray.internal.free``): one ``free_objects``
        RPC per holder node batching that node's ids. Lineage is NOT
        consulted — a freed object is gone even if its producer could
        rerun. Returns the number of node-level free RPCs that landed.
        """
        by_address: Dict[str, List[bytes]] = {}
        for ref in refs:
            reply = self.gcs.call("object_locations",
                                  object_id=ref.object_id, timeout=10.0)
            for loc in reply["locations"]:
                by_address.setdefault(loc["address"], []).append(
                    ref.object_id)
            with self._lock:
                self._lineage.pop(ref.object_id, None)
                self._retries.pop(ref.object_id, None)
        landed = 0
        for address, object_ids in by_address.items():
            try:
                self._raylet(address).call(
                    "free_objects", object_ids=object_ids, timeout=30.0)
                landed += 1
            except (RpcConnectionError, TimeoutError) as e:
                # holder died mid-free: its store dies with it and the
                # GCS drops the locations on node death
                logger.debug("free_objects on %s failed: %r", address, e)
        return landed

    # ------------------------------------------------------------------- kv
    def kv_put(self, key: bytes, value: bytes, ns: str = "default") -> None:
        self.gcs.call("kv_put", ns=ns, key=key, value=value, timeout=10.0)

    def kv_get(self, key: bytes, ns: str = "default") -> Optional[bytes]:
        return self.gcs.call("kv_get", ns=ns, key=key, timeout=10.0)

    def kv_del(self, key: bytes, ns: str = "default") -> bool:
        reply = self.gcs.call("kv_del", ns=ns, key=key, timeout=10.0)
        return bool(reply["deleted"])

    def kv_keys(self, prefix: bytes = b"", ns: str = "default"
                ) -> List[bytes]:
        return self.gcs.call("kv_keys", ns=ns, prefix=prefix,
                             timeout=10.0)

    # ------------------------------------------------------------- overview
    def job_view(self) -> dict:
        """Cluster-wide object/actor/PG counts (the `ray status`
        summary surface)."""
        return self.gcs.call("job_view", timeout=10.0)

    def close(self) -> None:
        self.gcs.close()
        for c in self._raylet_clients.values():
            c.close()
